"""vtpu-metricsd — per-tenant virtualized libtpu MetricService.

The reference's flagship transparency trick is lying to the *stock*
monitoring tool: its NVML hooks make an unmodified ``nvidia-smi`` report
only the container's quota (SURVEY §2.9f).  The TPU analogue of NVML is
libtpu's localhost gRPC metrics service on port 8431, which the stock
``tpu-info`` CLI reads.  This subsystem implements that protocol
(``proto/tpu_metrics.proto``) and serves QUOTA-VIRTUALIZED answers:

  - HBM total   = the tenant's HBM limit (not the raw chip capacity),
  - HBM usage   = the tenant's accounted ledger usage (the vtpucore
    shared region / broker STATS — the same source of truth as
    ``vtpu-smi``),
  - duty cycle  = the tenant's own device time, rescaled so 100% means
    "my full core quota", and
  - devices     = only the ordinals of the grant (TPU_VISIBLE_CHIPS /
    VTPU_DEVICE_MAP), never co-tenants' chips.

Non-sensitive metrics (uptime, versions) are proxied through to the real
libtpu service when one is running (moved off 8431 by the daemon's
``TPU_RUNTIME_METRICS_PORTS`` injection); anything that would disclose
raw capacity or co-tenant load is always answered virtualized.  A fake
backend (``backend.FakeBackend``) makes the whole path testable on
CPU-only CI.  Full protocol coverage, threat model and pass-through
rules: docs/METRICSD.md.
"""

from __future__ import annotations

# RPC registry — machine-checked by `vtpu-smi analyze` (tools/analyze/
# verbs.py): every RPC named here must have a stub binding and a servicer
# method in proto/tpu_metrics_grpc.py AND an implementation override in
# metricsd/server.py; an RPC implemented but not registered fails too.
METRICSD_RPCS = ("GetRuntimeMetric", "ListSupportedMetrics")

# Stock tpu-info dials localhost:8431; vtpu-metricsd binds it and the
# real libtpu service (if any) is moved to 8431 + OFFSET via
# TPU_RUNTIME_METRICS_PORTS at Allocate, where metricsd proxies it.
DEFAULT_PORT = 8431
UPSTREAM_PORT_OFFSET = 10
