"""``python -m vtpu.metricsd`` — run the virtualized MetricService."""

from .server import main

if __name__ == "__main__":
    raise SystemExit(main())
