"""Metric backends for vtpu-metricsd.

A backend answers one question: what does THIS tenant's grant look like
right now — per granted device ordinal, the HBM quota, the ledger usage,
the raw chip capacity and the tenant's own duty cycle.  The server layer
(metricsd/server.py) turns that into wire metrics; the virtualization
rules (clamp, scale, filter) live there so every backend benefits.

Backends:

  - ``RegionBackend``: the production path.  Reads the vtpucore shared
    accounting region named by the Allocate env contract — the same
    source of truth ``vtpu-smi`` and the replacement ``tpu-info`` read.
    BIND-FREE by design: it never registers a process slot and never
    speaks HELLO to the broker, so a metrics probe can never claim a
    chip or wedge a tenant slot (the PR-1 STATS lesson).  Optionally
    enriches usage from the broker's bind-free STATS verb for brokered
    grants whose ledger lives broker-side.
  - ``FakeBackend``: deterministic synthetic tenant for CPU-only CI and
    the ``--selftest`` smoke — no native lib, no region file needed.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from ..utils import envspec
from ..utils import logging as log

# Raw per-chip HBM capacity fallback when the discovery inventory is not
# available in-container (v5e-class default; the real total only shows
# for UNLIMITED grants, a quota-bearing grant reports the quota).
_RAW_HBM_FALLBACK = 16 * 2**30


@dataclass
class DeviceView:
    """One granted device ordinal as the tenant may see it."""

    ordinal: int
    chip_id: str = ""
    hbm_limit_bytes: int = 0       # 0 = unlimited grant
    hbm_used_bytes: int = 0
    hbm_raw_total_bytes: int = _RAW_HBM_FALLBACK
    duty_cycle_pct: float = 0.0    # tenant's own, of the WHOLE chip
    core_limit_pct: int = 0        # 0 = no core quota


class Backend:
    def devices(self) -> List[DeviceView]:
        raise NotImplementedError

    def slo_summary(self) -> Optional[Dict]:
        """The tenant's OWN SLO view for the virtualized wire
        (docs/OBSERVABILITY.md): ``{"attainment_pct", "p99_us",
        "target_us", "burn_rate"}`` or None when no SLO source exists.
        Like duty, the numbers are already tenant-relative ("of my
        objective") — nothing about co-tenants or the raw chip leaks
        through this surface."""
        return None

    def close(self) -> None:
        pass


class RegionBackend(Backend):
    """Shared-region-backed tenant view (bind-free: stats reads only)."""

    def __init__(self, region_path: Optional[str] = None,
                 quota: Optional[envspec.QuotaSpec] = None,
                 broker_socket: Optional[str] = None,
                 tenant: Optional[str] = None):
        self.quota = quota if quota is not None else envspec.quota_from_env()
        self.region_path = region_path or self.quota.shared_cache
        self.broker_socket = broker_socket
        self.tenant = tenant
        # Duty cycle needs two samples: ordinal -> (busy_us, t).
        self._prev: Dict[int, tuple] = {}

    # -- region --

    def _open_region(self):
        """Fresh open per sample: the region file can be recreated under
        pod churn, and holding no fd keeps the probe side-effect free.
        Never registers a proc slot — stats stay bind-free."""
        if not self.region_path or not os.path.exists(self.region_path):
            return None
        from ..shim.core import SharedRegion
        try:
            return SharedRegion(self.region_path)
        except OSError as e:
            log.warn("metricsd: region %s unreadable: %s",
                     self.region_path, e)
            return None

    def _broker_usage(self) -> Optional[Dict]:
        """Per-tenant ledger from the broker's BIND-FREE STATS verb on
        the MAIN socket (no HELLO, no tenant slot, no chip claim)."""
        if not self.broker_socket:
            return None
        from ..runtime import protocol as P
        from ..tools.vtpu_smi import _main_request
        try:
            resp = _main_request(self.broker_socket, {"kind": P.STATS},
                                 timeout=2.0)
        except (OSError, P.ProtocolError) as e:
            log.warn("metricsd: broker %s unreachable: %s",
                     self.broker_socket, e)
            return None
        if not resp.get("ok"):
            return None
        tenants = resp.get("tenants", {})
        if self.tenant:
            return tenants.get(self.tenant)
        if len(tenants) == 1:
            return next(iter(tenants.values()))
        return None

    def _ordinals(self, region) -> List[int]:
        if self.quota.device_map:
            return [e.ordinal for e in self.quota.device_map]
        if region is not None:
            return list(range(region.ndevices))
        ords = sorted(self.quota.hbm_limit_bytes)
        return [o for o in ords if o >= 0] or [0]

    def devices(self) -> List[DeviceView]:
        region = self._open_region()
        chip_of = {e.ordinal: e.chip_uuid for e in self.quota.device_map}
        broker = self._broker_usage()
        now = time.monotonic()
        out: List[DeviceView] = []
        try:
            for o in self._ordinals(region):
                view = DeviceView(ordinal=o, chip_id=chip_of.get(o, ""))
                view.hbm_limit_bytes = self.quota.limit_for(o)
                view.core_limit_pct = self.quota.core_limit_pct
                if region is not None and o < region.ndevices:
                    st = region.device_stats(o)
                    if st.limit_bytes:
                        view.hbm_limit_bytes = int(st.limit_bytes)
                    view.hbm_used_bytes = int(st.used_bytes)
                    if st.core_limit_pct:
                        view.core_limit_pct = int(st.core_limit_pct)
                    prev = self._prev.get(o)
                    self._prev[o] = (int(st.busy_us), now)
                    if prev is not None and now > prev[1]:
                        duty = (int(st.busy_us) - prev[0]) \
                            / ((now - prev[1]) * 1e6) * 100.0
                        view.duty_cycle_pct = min(max(duty, 0.0), 100.0)
                out.append(view)
        finally:
            if region is not None:
                region.close()
        # Brokered grants: the ledger lives broker-side; its usage wins
        # over a region the interposer never touched (used == 0).
        if broker and out and not any(v.hbm_used_bytes for v in out):
            per_chip = broker.get("per_chip")
            if isinstance(per_chip, list) and per_chip:
                # Grant order matches the tenant's ordinal order: the
                # i-th broker chip is the i-th granted ordinal.
                for view, pc in zip(out, per_chip):
                    view.hbm_used_bytes = int(pc.get("used_bytes", 0))
                    lim = int(pc.get("limit_bytes", 0))
                    if lim and not view.hbm_limit_bytes:
                        view.hbm_limit_bytes = lim
            else:
                # Pre-per_chip broker: the ledger is aggregate-only.
                # Attribute it evenly rather than dumping the whole
                # grant's usage on ordinal 0.
                used = int(broker.get("used_bytes", 0))
                limit = int(broker.get("limit_bytes", 0))
                n = len(out)
                for i, view in enumerate(out):
                    view.hbm_used_bytes = \
                        used // n + (1 if i < used % n else 0)
                    if limit and not view.hbm_limit_bytes:
                        view.hbm_limit_bytes = \
                            limit // n + (1 if i < limit % n else 0)
        return out


    def slo_summary(self) -> Optional[Dict]:
        """Bind-free SLO read on the broker MAIN socket: the probe
        names its own tenant explicitly (no HELLO, no slot, no chip
        claim — the STATS rule) and gets exactly that row back."""
        if not self.broker_socket or not self.tenant:
            return None
        from ..runtime import protocol as P
        from ..tools.vtpu_smi import _main_request
        try:
            resp = _main_request(
                self.broker_socket,
                {"kind": P.SLO, "tenant": self.tenant}, timeout=2.0)
        except (OSError, P.ProtocolError) as e:
            log.warn("metricsd: broker %s SLO read failed: %s",
                     self.broker_socket, e)
            return None
        if not resp.get("ok") or not resp.get("enabled"):
            return None
        row = (resp.get("tenants") or {}).get(self.tenant)
        if not row:
            return None
        wins = row.get("windows") or {}
        short = wins[min(wins, key=float)] if wins else {}
        return {
            "attainment_pct": float(short.get("attainment_pct", 100.0)),
            "p99_us": float((row.get("phases") or {})
                            .get("e2e", {}).get("p99_us", 0.0)),
            "target_us": float((row.get("objective") or {})
                               .get("target_us", 0.0)),
            "burn_rate": float(short.get("burn_rate", 0.0)),
        }


class FakeBackend(Backend):
    """Deterministic synthetic tenant (CPU CI / --selftest).

    Defaults model the canonical acceptance scenario: a 16 GiB chip
    granted at 50% HBM / 50% core, with the ledger at 1 GiB and the
    tenant running at 40% of the whole chip (=> 80% of its quota)."""

    def __init__(self, n_devices: int = 2,
                 hbm_limit_bytes: int = 8 * 2**30,
                 hbm_raw_total_bytes: int = 16 * 2**30,
                 hbm_used_bytes: int = 1 * 2**30,
                 duty_cycle_pct: float = 40.0,
                 core_limit_pct: int = 50,
                 generation: str = "v5e"):
        self.n_devices = n_devices
        self.hbm_limit_bytes = hbm_limit_bytes
        self.hbm_raw_total_bytes = hbm_raw_total_bytes
        self.hbm_used_bytes = hbm_used_bytes
        self.duty_cycle_pct = duty_cycle_pct
        self.core_limit_pct = core_limit_pct
        self.generation = generation

    @classmethod
    def from_env(cls, env: Optional[Dict[str, str]] = None) -> "FakeBackend":
        """Honor the quota env contract when present so a fake-backend
        container still reflects its Allocate grant; fall back to the
        canonical 50%/50% scenario."""
        e = dict(os.environ if env is None else env)
        spec = envspec.quota_from_env(e)
        n = len(spec.device_map) or int(e.get("VTPU_FAKE_CHIPS", "2"))
        kw = {}
        if spec.limit_for(0):
            kw["hbm_limit_bytes"] = spec.limit_for(0)
        if spec.core_limit_pct:
            kw["core_limit_pct"] = spec.core_limit_pct
        return cls(n_devices=n,
                   generation=e.get("VTPU_FAKE_GENERATION", "v5e"), **kw)

    def devices(self) -> List[DeviceView]:
        return [
            DeviceView(
                ordinal=i,
                chip_id=f"TPU-fake-{self.generation}-{i:02d}",
                hbm_limit_bytes=self.hbm_limit_bytes,
                hbm_used_bytes=self.hbm_used_bytes,
                hbm_raw_total_bytes=self.hbm_raw_total_bytes,
                duty_cycle_pct=self.duty_cycle_pct,
                core_limit_pct=self.core_limit_pct,
            )
            for i in range(self.n_devices)
        ]

    def slo_summary(self) -> Optional[Dict]:
        """Canonical synthetic SLO: 95% attainment against a 50ms
        objective, e2e p99 at 42ms, burn 0.5 — the selftest numbers."""
        return {"attainment_pct": 95.0, "p99_us": 42_000.0,
                "target_us": 50_000.0, "burn_rate": 0.5}
