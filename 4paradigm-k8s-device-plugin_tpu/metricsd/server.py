"""The vtpu-metricsd gRPC server: libtpu MetricService, quota-virtualized.

Serves ``tpu.monitoring.runtime.v2alpha1.RuntimeMetricService`` (the
protocol stock ``tpu-info`` speaks to localhost:8431) with the tenant's
view instead of the raw chip's:

  =============================  =========================================
  metric                         virtualization rule
  =============================  =========================================
  hbm.memory.total.bytes         the HBM quota (raw capacity only for an
                                 unlimited grant); never the chip total
  hbm.memory.usage.bytes         the tenant's ledger usage, clamped to
                                 the reported total
  tensorcore.dutycycle.percent   the tenant's own device time, rescaled
                                 by the core quota so 100% = "all of MY
                                 share" (a 50% tenant running flat out
                                 reads 100, not 50)
  (device enumeration)           granted ordinals only — co-tenant chips
                                 do not exist on this wire
  =============================  =========================================

Everything else is either proxied to the real libtpu service (moved off
8431 by the daemon's ``TPU_RUNTIME_METRICS_PORTS`` injection) when its
name is provably non-sensitive, or answered NOT_FOUND.  Pass-through is
deny-by-default: a metric name matching any raw-capacity/-utilization
pattern is NEVER forwarded (docs/METRICSD.md, "Pass-through rules").

Started per-container by the shim bootstrap (``maybe_start_in_container``
from sitecustomize, port race = first process wins) or standalone::

    python -m vtpu.metricsd --port 8431            # region backend (env)
    python -m vtpu.metricsd --fake --port 8431     # CPU CI fake backend
    python -m vtpu.metricsd --selftest             # e2e smoke, exits 0/1
"""

from __future__ import annotations

import argparse
import os
import threading
import time
from concurrent import futures
from typing import Dict, List, Optional

from ..utils import logging as log
from . import DEFAULT_PORT, UPSTREAM_PORT_OFFSET
from .backend import Backend, DeviceView, FakeBackend, RegionBackend

# Wire metric names (the set stock tpu-info queries).
METRIC_HBM_TOTAL = "tpu.runtime.hbm.memory.total.bytes"
METRIC_HBM_USAGE = "tpu.runtime.hbm.memory.usage.bytes"
METRIC_DUTY_CYCLE = "tpu.runtime.tensorcore.dutycycle.percent"
# vtpu-slo (docs/OBSERVABILITY.md): the tenant's OWN SLO, served on
# the stock wire so an in-container scrape sees its attainment the
# same place it sees its (virtualized) HBM — rescaled like duty:
# attainment is "of MY objective", never a co-tenant's number.
METRIC_SLO_ATTAINMENT = "vtpu.slo.attainment.percent"
METRIC_SLO_P99 = "vtpu.slo.e2e.p99.microseconds"
# metricsd self-gauges, served on the same wire so node tooling
# (tools/metrics_server.py --metricsd) can scrape them without a side
# channel.
METRIC_SELF_REQUESTS = "vtpu.metricsd.requests.total"
METRIC_SELF_PASSTHROUGH = "vtpu.metricsd.passthrough.total"
METRIC_SELF_DENIED = "vtpu.metricsd.passthrough.denied.total"

VIRTUALIZED_METRICS = (METRIC_HBM_TOTAL, METRIC_HBM_USAGE,
                       METRIC_DUTY_CYCLE, METRIC_SLO_ATTAINMENT,
                       METRIC_SLO_P99)
SELF_METRICS = (METRIC_SELF_REQUESTS, METRIC_SELF_PASSTHROUGH,
                METRIC_SELF_DENIED)

# Deny-by-default pass-through: any metric name containing one of these
# substrings discloses raw capacity or co-tenant load and is never
# forwarded, whatever the upstream offers (docs/METRICSD.md).
SENSITIVE_PATTERNS = ("hbm", "memory", "dutycycle", "duty_cycle",
                      "utilization", "tensorcore", "bandwidth", "power")


def is_sensitive(name: str) -> bool:
    low = name.lower()
    return any(p in low for p in SENSITIVE_PATTERNS)


def virtual_duty_pct(raw_pct: float, core_limit_pct: int) -> float:
    """Rescale the tenant's whole-chip duty to quota-relative: with a
    50% core quota, 40% of the chip reads as 80% "of my share"."""
    if core_limit_pct <= 0:
        return min(max(raw_pct, 0.0), 100.0)
    return min(max(raw_pct, 0.0) * 100.0 / core_limit_pct, 100.0)


class MetricsdServicer:
    """RuntimeMetricService implementation over a tenant Backend."""

    def __init__(self, backend: Backend,
                 upstream: Optional[str] = None):
        from ..proto import tpu_metrics_grpc as mrpc
        from ..proto import tpu_metrics_pb2 as mpb
        self.mpb = mpb
        self.mrpc = mrpc
        self.backend = backend
        self.upstream = upstream
        self._upstream_stub = None
        self._upstream_mu = threading.Lock()
        self.started_at = time.time()
        # Self-gauges (also folded into tools/metrics_server.py).
        self.requests_total = 0
        self.passthrough_total = 0
        self.passthrough_denied_total = 0
        self.stats_mu = threading.Lock()

    # -- upstream proxy --

    def _stub(self):
        if not self.upstream:
            return None
        with self._upstream_mu:
            if self._upstream_stub is None:
                import grpc
                ch = grpc.insecure_channel(self.upstream)
                self._upstream_stub = self.mrpc.RuntimeMetricServiceStub(ch)
            return self._upstream_stub

    def _passthrough(self, request, context):
        import grpc
        stub = self._stub()
        if stub is None:
            context.set_code(grpc.StatusCode.NOT_FOUND)
            context.set_details(
                f"unknown metric {request.metric_name!r} "
                f"(no upstream libtpu service)")
            return self.mpb.MetricResponse()
        try:
            resp = stub.GetRuntimeMetric(request, timeout=2.0)
        except grpc.RpcError as e:
            context.set_code(grpc.StatusCode.NOT_FOUND)
            context.set_details(
                f"upstream libtpu service: {e.code().name}")
            return self.mpb.MetricResponse()
        with self.stats_mu:
            self.passthrough_total += 1
        return resp

    # -- virtualized answers --

    def _gauge_metric(self, name: str, views: List[DeviceView],
                      value_of) -> "object":
        resp = self.mpb.MetricResponse()
        resp.metric.name = name
        for v in views:
            m = resp.metric.metrics.add()
            m.attribute.key = "device-id"
            m.attribute.value.int_attr = v.ordinal
            m.timestamp.GetCurrentTime()
            val = value_of(v)
            if isinstance(val, float):
                m.gauge.as_double = val
            else:
                m.gauge.as_int = int(val)
        return resp

    def _self_metric(self, name: str) -> "object":
        resp = self.mpb.MetricResponse()
        resp.metric.name = name
        m = resp.metric.metrics.add()
        m.timestamp.GetCurrentTime()
        with self.stats_mu:
            vals = {
                METRIC_SELF_REQUESTS: self.requests_total,
                METRIC_SELF_PASSTHROUGH: self.passthrough_total,
                METRIC_SELF_DENIED: self.passthrough_denied_total,
            }
        m.gauge.as_int = int(vals[name])
        return resp

    # -- RPCs (registry: metricsd/__init__.py METRICSD_RPCS) --

    def GetRuntimeMetric(self, request, context):
        with self.stats_mu:
            self.requests_total += 1
        name = request.metric_name
        if name in SELF_METRICS:
            return self._self_metric(name)
        if name == METRIC_HBM_TOTAL:
            return self._gauge_metric(
                name, self.backend.devices(),
                lambda v: v.hbm_limit_bytes or v.hbm_raw_total_bytes)
        if name == METRIC_HBM_USAGE:
            return self._gauge_metric(
                name, self.backend.devices(),
                lambda v: min(v.hbm_used_bytes,
                              v.hbm_limit_bytes or v.hbm_raw_total_bytes))
        if name == METRIC_DUTY_CYCLE:
            return self._gauge_metric(
                name, self.backend.devices(),
                lambda v: float(virtual_duty_pct(v.duty_cycle_pct,
                                                 v.core_limit_pct)))
        if name in (METRIC_SLO_ATTAINMENT, METRIC_SLO_P99):
            # Tenant-virtualized SLO (docs/OBSERVABILITY.md): the
            # tenant's own attainment/p99 reported per granted ordinal
            # (the grant's SLO is tenant-level; each granted device
            # shows it, the way duty shows the rescaled share).  No
            # SLO source -> empty metric, never an error.
            slo = self.backend.slo_summary()
            resp = self.mpb.MetricResponse()
            resp.metric.name = name
            if slo is not None:
                val = (float(slo.get("attainment_pct", 100.0))
                       if name == METRIC_SLO_ATTAINMENT
                       else float(slo.get("p99_us", 0.0)))
                for v in self.backend.devices():
                    m = resp.metric.metrics.add()
                    m.attribute.key = "device-id"
                    m.attribute.value.int_attr = v.ordinal
                    m.timestamp.GetCurrentTime()
                    m.gauge.as_double = val
            return resp
        if is_sensitive(name):
            # Never forwarded: a raw-capacity metric the virtualizer does
            # not model must not leak through the proxy either.
            import grpc
            with self.stats_mu:
                self.passthrough_denied_total += 1
            context.set_code(grpc.StatusCode.NOT_FOUND)
            context.set_details(
                f"metric {name!r} is quota-sensitive and not virtualized")
            return self.mpb.MetricResponse()
        return self._passthrough(request, context)

    def ListSupportedMetrics(self, request, context):
        with self.stats_mu:
            self.requests_total += 1
        resp = self.mpb.ListSupportedMetricsResponse()
        names = list(VIRTUALIZED_METRICS) + list(SELF_METRICS)
        stub = self._stub()
        if stub is not None:
            import grpc
            try:
                up = stub.ListSupportedMetrics(
                    self.mpb.ListSupportedMetricsRequest(), timeout=2.0)
                for sm in up.supported_metric:
                    if sm.metric_name not in names \
                            and not is_sensitive(sm.metric_name):
                        names.append(sm.metric_name)
            except grpc.RpcError:
                pass  # upstream down: advertise the virtualized set only
        for n in names:
            resp.supported_metric.add().metric_name = n
        return resp


def make_server(port: int, backend: Backend, host: str = "127.0.0.1",
                upstream: Optional[str] = None):
    """Build + start a metricsd gRPC server; returns (server, servicer,
    bound_port).  port=0 binds an ephemeral port (tests)."""
    import grpc

    from ..proto import tpu_metrics_grpc as mrpc
    servicer = MetricsdServicer(backend, upstream=upstream)
    # so_reuseport OFF: the per-container singleton is a port-bind RACE
    # (maybe_start_in_container) — with grpc's default SO_REUSEPORT every
    # process would "win" the bind and a container would run one server
    # per Python process.
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=4,
                                   thread_name_prefix="vtpu-metricsd"),
        options=[("grpc.so_reuseport", 0)])
    mrpc.add_RuntimeMetricServiceServicer_to_server(servicer, server)
    bound = server.add_insecure_port(f"{host}:{port}")
    if bound == 0:
        raise OSError(f"metricsd cannot bind {host}:{port}")
    server.start()
    return server, servicer, bound


def backend_from_env(env: Optional[Dict[str, str]] = None) -> Backend:
    e = dict(os.environ if env is None else env)
    if e.get("VTPU_METRICSD_FAKE") == "1":
        return FakeBackend.from_env(e)
    return RegionBackend(
        broker_socket=e.get("VTPU_METRICSD_BROKER")
        or e.get("VTPU_RUNTIME_SOCKET"),
        tenant=e.get("VTPU_TENANT"))


def upstream_from_env(e: Dict[str, str], port: int) -> Optional[str]:
    """Pass-through target: explicit VTPU_METRICSD_UPSTREAM wins; else
    the first TPU_RUNTIME_METRICS_PORTS entry (where Allocate moved the
    real libtpu service) unless it is our own port."""
    explicit = e.get("VTPU_METRICSD_UPSTREAM")
    if explicit:
        return explicit
    raw = (e.get("TPU_RUNTIME_METRICS_PORTS") or "").split(",")[0].strip()
    if raw.isdigit() and int(raw) != port:
        return f"localhost:{raw}"
    return None


_started = None
_started_mu = threading.Lock()


def maybe_start_in_container():
    """Shim-bootstrap entry (sitecustomize): serve the tenant's metricsd
    when the Allocate contract asked for one.  Per-container singleton by
    port-bind race — every process tries, the first bind wins, the rest
    skip silently.  Never raises: a broken metricsd must not take down
    user containers."""
    global _started
    e = os.environ
    port_s = e.get("VTPU_METRICSD_PORT", "")
    if not port_s or e.get("VTPU_METRICSD_AUTOSTART", "1") == "0":
        return None
    with _started_mu:
        if _started is not None:
            return _started
        try:
            port = int(port_s)
            upstream = upstream_from_env(dict(e), port)
            server, servicer, bound = make_server(
                port, backend_from_env(), upstream=upstream)
        except (OSError, ValueError, RuntimeError):
            # Port taken (grpc surfaces the failed bind as RuntimeError):
            # a sibling process already serves this container's metricsd
            # (the common fork/exec case).
            return None
        except Exception as exc:  # noqa: BLE001 - never break user startup
            log.warn("metricsd bootstrap failed: %s", exc)
            return None
        _started = (server, servicer, bound)
        log.info("vtpu-metricsd serving MetricService on 127.0.0.1:%d%s",
                 bound, f" (pass-through {upstream})" if upstream else "")
        return _started


def selftest() -> int:
    """CPU-only e2e smoke (CI): stock-protocol client against a fake
    50% HBM / 50% core tenant; asserts the quota clamp end to end."""
    import grpc

    from ..proto import tpu_metrics_grpc as mrpc
    from ..proto import tpu_metrics_pb2 as mpb
    backend = FakeBackend()  # 16 GiB chip, 8 GiB/50% grant, duty 40%
    server, _, port = make_server(0, backend)
    try:
        ch = grpc.insecure_channel(f"localhost:{port}")
        stub = mrpc.RuntimeMetricServiceStub(ch)
        total = stub.GetRuntimeMetric(
            mpb.MetricRequest(metric_name=METRIC_HBM_TOTAL), timeout=5)
        usage = stub.GetRuntimeMetric(
            mpb.MetricRequest(metric_name=METRIC_HBM_USAGE), timeout=5)
        duty = stub.GetRuntimeMetric(
            mpb.MetricRequest(metric_name=METRIC_DUTY_CYCLE), timeout=5)
        listed = stub.ListSupportedMetrics(
            mpb.ListSupportedMetricsRequest(), timeout=5)
        ch.close()
        ok = (
            len(total.metric.metrics) == backend.n_devices
            and all(m.gauge.as_int == backend.hbm_limit_bytes
                    for m in total.metric.metrics)
            and all(m.gauge.as_int == backend.hbm_used_bytes
                    for m in usage.metric.metrics)
            and all(abs(m.gauge.as_double - 80.0) < 1e-6
                    for m in duty.metric.metrics)
            and {METRIC_HBM_TOTAL, METRIC_HBM_USAGE, METRIC_DUTY_CYCLE}
            <= {sm.metric_name for sm in listed.supported_metric}
        )
        print("metricsd selftest:",
              "ok — stock client sees 8 GiB total / 1 GiB used / 80% "
              "of-quota duty on 2 granted devices" if ok else "FAILED")
        return 0 if ok else 1
    finally:
        server.stop(grace=0.5)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="vtpu-metricsd",
        description="per-tenant virtualized libtpu MetricService")
    ap.add_argument("--port", type=int,
                    default=int(os.environ.get("VTPU_METRICSD_PORT",
                                               str(DEFAULT_PORT))))
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--region", default=None,
                    help="explicit accounting region (default: the "
                         "Allocate env contract)")
    ap.add_argument("--broker", default=None, metavar="SOCKET",
                    help="broker MAIN socket for bind-free STATS ledger "
                         "enrichment")
    ap.add_argument("--upstream", default=None, metavar="HOST:PORT",
                    help="real libtpu MetricService for non-sensitive "
                         "pass-through")
    ap.add_argument("--fake", action="store_true",
                    help="synthetic tenant backend (CPU CI)")
    ap.add_argument("--selftest", action="store_true",
                    help="start a fake-backend server, query it with a "
                         "stock-protocol client, assert the quota clamp")
    ns = ap.parse_args(argv)
    if ns.selftest:
        return selftest()
    if ns.fake:
        backend: Backend = FakeBackend.from_env()
    else:
        backend = RegionBackend(
            region_path=ns.region,
            broker_socket=ns.broker
            or os.environ.get("VTPU_METRICSD_BROKER"),
            tenant=os.environ.get("VTPU_TENANT"))
    upstream = ns.upstream or upstream_from_env(dict(os.environ), ns.port)
    try:
        server, _, bound = make_server(ns.port, backend, host=ns.host,
                                       upstream=upstream)
    except (OSError, RuntimeError) as e:
        log.error("vtpu-metricsd cannot bind %s:%d: %s",
                  ns.host, ns.port, e)
        return 1
    log.info("vtpu-metricsd serving on %s:%d (upstream: %s)",
             ns.host, bound, upstream or "none")
    try:
        server.wait_for_termination()
    except KeyboardInterrupt:
        server.stop(grace=1)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
