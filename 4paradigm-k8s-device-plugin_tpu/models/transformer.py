"""Decoder-only transformer (Llama-style) — the flagship JAX workload.

Exists as a *client* of the vTPU framework (the reference validates its
interceptor against TensorFlow/torch workloads, README.md:213-222; our
equivalents are JAX models): bench.py runs it under quota enforcement, and
__graft_entry__ uses it for the single-chip forward and the multi-chip
sharded training dry-run.

TPU-first choices: bf16 activations/weights with f32 RMSNorm accumulation,
RoPE, SwiGLU, GQA; weights carry ('dp','tp') PartitionSpecs laid out so
tensor-parallel collectives (psum over 'tp') ride ICI — attention heads
and MLP hidden are split over 'tp', embeddings replicated, batch over
'dp'.  Static shapes throughout; the decode cache is a fixed-size buffer
updated with lax.dynamic_update_slice so jit never retraces.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 32000
    dim: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 4
    hidden: int = 1408          # SwiGLU hidden (~2.75x dim)
    max_seq: int = 1024
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    # Pallas fused-attention kernel (vtpu.ops.flash_attention); the jnp
    # reference path stays default for sharded training (the kernel is a
    # single-device op — round-2: shard_map it over 'tp').
    use_flash: bool = False

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @staticmethod
    def tiny() -> "TransformerConfig":
        return TransformerConfig(vocab=256, dim=64, n_layers=2, n_heads=4,
                                 n_kv_heads=2, hidden=192, max_seq=128)

    @staticmethod
    def llama_8b_proportions(layers: int = 4) -> "TransformerConfig":
        """Llama-3-8B shapes with a truncated layer stack (single-chip
        bench keeps HBM bounded; full depth = 32)."""
        return TransformerConfig(vocab=128256, dim=4096, n_layers=layers,
                                 n_heads=32, n_kv_heads=8, hidden=14336,
                                 max_seq=2048)

    @staticmethod
    def llama3_8b() -> "TransformerConfig":
        """Full Llama-3-8B geometry (32 layers, GQA 32/8, 128k vocab,
        rope 500k): the multi-chip serving target (BASELINE config 5 —
        tp-sharded over a v5e-8 slice; the full geometry's sharded
        lowering is exercised abstractly by
        tests/test_multichip_e2e.py::test_llama3_8b_sharded_lowering,
        runtime sharding on tiny shapes by the dryrun)."""
        return TransformerConfig(vocab=128256, dim=4096, n_layers=32,
                                 n_heads=32, n_kv_heads=8, hidden=14336,
                                 max_seq=8192, rope_theta=500000.0)

    @staticmethod
    def bench() -> "TransformerConfig":
        """Llama-3-8B layer geometry, reduced vocab + depth so 4 tenant
        replicas (~1 GB bf16 each) co-reside on one 16 GB v5e chip with
        activations and params upload in reasonable time — matmul-
        dominant, MXU-bound."""
        return TransformerConfig(vocab=8192, dim=4096, n_layers=2,
                                 n_heads=32, n_kv_heads=8, hidden=14336,
                                 max_seq=2048)


# Parameter PartitionSpecs: dim-sharded over 'tp' on the contraction-free
# axis, replicated elsewhere.  (The scaling-book recipe: annotate, let XLA
# insert the collectives.)
def param_specs(cfg: TransformerConfig) -> Dict[str, Any]:
    layer = {
        "attn_norm": P(),
        "mlp_norm": P(),
        "wq": P(None, "tp"),
        "wk": P(None, "tp"),
        "wv": P(None, "tp"),
        "wo": P("tp", None),
        "w_gate": P(None, "tp"),
        "w_up": P(None, "tp"),
        "w_down": P("tp", None),
    }
    return {
        "embed": P(),
        "final_norm": P(),
        "lm_head": P(None, "tp"),
        "layers": [dict(layer) for _ in range(cfg.n_layers)],
    }


def init_params(cfg: TransformerConfig, key: jax.Array) -> Dict[str, Any]:
    k_embed, k_head, *k_layers = jax.random.split(key, 2 + cfg.n_layers)
    scale = cfg.dim ** -0.5
    dt = cfg.dtype

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(dt)

    params: Dict[str, Any] = {
        "embed": (jax.random.normal(k_embed, (cfg.vocab, cfg.dim),
                                    jnp.float32) * scale).astype(dt),
        "final_norm": jnp.ones((cfg.dim,), jnp.float32),
        "lm_head": dense(k_head, (cfg.dim, cfg.vocab), cfg.dim),
        "layers": [],
    }
    kv_dim = cfg.n_kv_heads * cfg.head_dim
    for kl in k_layers:
        ks = jax.random.split(kl, 7)
        params["layers"].append({
            "attn_norm": jnp.ones((cfg.dim,), jnp.float32),
            "mlp_norm": jnp.ones((cfg.dim,), jnp.float32),
            "wq": dense(ks[0], (cfg.dim, cfg.dim), cfg.dim),
            "wk": dense(ks[1], (cfg.dim, kv_dim), cfg.dim),
            "wv": dense(ks[2], (cfg.dim, kv_dim), cfg.dim),
            "wo": dense(ks[3], (cfg.dim, cfg.dim), cfg.dim),
            "w_gate": dense(ks[4], (cfg.dim, cfg.hidden), cfg.dim),
            "w_up": dense(ks[5], (cfg.dim, cfg.hidden), cfg.dim),
            "w_down": dense(ks[6], (cfg.hidden, cfg.dim), cfg.hidden),
        })
    return params


def rmsnorm(x: jax.Array, w: jax.Array) -> jax.Array:
    xf = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + 1e-6)
    return ((xf * rms) * w).astype(x.dtype)


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3))
def _rope_tables(theta: float, dtype, seq: int, head_dim: int):
    pos = jnp.arange(seq, dtype=jnp.float32)[:, None]
    freq = theta ** (-jnp.arange(0, head_dim, 2, jnp.float32) / head_dim)
    ang = pos * freq[None, :]
    return jnp.cos(ang).astype(dtype), jnp.sin(ang).astype(dtype)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    # x: [b, s, h, d]; tables: [s, d/2]
    x1, x2 = jnp.split(x, 2, axis=-1)
    c = cos[None, :, None, :]
    s = sin[None, :, None, :]
    return jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)


def attention(x: jax.Array, lp: Dict[str, jax.Array],
              cfg: TransformerConfig, cos, sin,
              mask: Optional[jax.Array]) -> jax.Array:
    b, s, _ = x.shape
    q = (x @ lp["wq"]).reshape(b, s, cfg.n_heads, cfg.head_dim)
    k = (x @ lp["wk"]).reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    v = (x @ lp["wv"]).reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    rep = cfg.n_heads // cfg.n_kv_heads
    k = jnp.repeat(k, rep, axis=2)
    v = jnp.repeat(v, rep, axis=2)
    if cfg.use_flash:
        from ..ops.flash_attention import attention_bshd

        out = attention_bshd(q, k, v, causal=True).reshape(b, s, cfg.dim)
        return out @ lp["wo"]
    # [b, h, s, d]: MXU-friendly contraction layout.
    q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    scores = scores * (cfg.head_dim ** -0.5)
    if mask is not None:
        scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    out = out.transpose(0, 2, 1, 3).reshape(b, s, cfg.dim)
    return out @ lp["wo"]


def mlp(x: jax.Array, lp: Dict[str, jax.Array]) -> jax.Array:
    return (jax.nn.silu(x @ lp["w_gate"]) * (x @ lp["w_up"])) @ lp["w_down"]


def forward(params: Dict[str, Any], tokens: jax.Array,
            cfg: TransformerConfig) -> jax.Array:
    """tokens [b, s] int32 -> logits [b, s, vocab] (causal LM)."""
    b, s = tokens.shape
    x = params["embed"][tokens]
    cos, sin = _rope_tables(cfg.rope_theta, cfg.dtype, s, cfg.head_dim)
    causal = jnp.tril(jnp.ones((s, s), bool))[None, None, :, :]
    for lp in params["layers"]:
        x = x + attention(rmsnorm(x, lp["attn_norm"]), lp, cfg, cos, sin,
                          causal)
        x = x + mlp(rmsnorm(x, lp["mlp_norm"]), lp)
    x = rmsnorm(x, params["final_norm"])
    return (x @ params["lm_head"]).astype(jnp.float32)


def loss_fn(params, tokens, cfg: TransformerConfig) -> jax.Array:
    """Next-token cross-entropy over the shifted sequence."""
    logits = forward(params, tokens[:, :-1], cfg)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return -jnp.mean(ll)


def shard_params(params, mesh: Mesh, cfg: TransformerConfig):
    specs = param_specs(cfg)
    return jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
        params, specs,
        is_leaf=lambda x: isinstance(x, jnp.ndarray) or hasattr(x, "shape"))


def make_train_step(cfg: TransformerConfig, mesh: Optional[Mesh] = None,
                    lr: float = 1e-3):
    """Adam training step; with a mesh, inputs are dp-sharded and params
    tp-sharded per param_specs — XLA inserts the psums over ICI."""
    import optax

    opt = optax.adam(lr)

    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, tokens, cfg))(params)
        updates, opt_state = opt.update(grads, opt_state)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    if mesh is None:
        return jax.jit(step), opt

    specs = param_specs(cfg)
    param_sh = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P))
    data_sh = NamedSharding(mesh, P("dp", None))
    jitted = jax.jit(
        step,
        in_shardings=(param_sh, None, data_sh),
        out_shardings=(param_sh, None, None),
    )
    return jitted, opt
