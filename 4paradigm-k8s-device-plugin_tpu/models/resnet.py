"""ResNet-V2 (pre-activation) in flax — the ai-benchmark parity workload.

The reference's published benchmark suite runs ResNet-V2-50/152 inference
and training under its vGPU quotas (reference README.md:58-71,
benchmarks/ai-benchmark/); these are the matching TPU client models that
bench.py drives under vTPU quotas.  bf16 activations, f32 batch-norm
statistics — the standard TPU recipe; NHWC layout (XLA:TPU's native conv
layout).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any


class BottleneckV2(nn.Module):
    """Pre-activation bottleneck (BN-ReLU-conv x3 + projection)."""

    filters: int
    strides: int = 1
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=jnp.float32)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)

        preact = nn.relu(norm()(x))
        shortcut = x
        if x.shape[-1] != self.filters * 4 or self.strides != 1:
            shortcut = conv(self.filters * 4, (1, 1),
                            strides=self.strides)(preact)

        y = conv(self.filters, (1, 1))(preact)
        y = nn.relu(norm()(y))
        y = conv(self.filters, (3, 3), strides=self.strides,
                 padding=[(1, 1), (1, 1)])(y)
        y = nn.relu(norm()(y))
        y = conv(self.filters * 4, (1, 1))(y)
        return shortcut + y


class ResNetV2(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(64, (7, 7), strides=2, padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=self.dtype)(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = BottleneckV2(64 * 2 ** i, strides=strides,
                                 dtype=self.dtype)(x, train=train)
        x = nn.relu(nn.BatchNorm(use_running_average=not train,
                                 dtype=jnp.float32)(x))
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def resnet_v2_50(**kw) -> ResNetV2:
    return ResNetV2(stage_sizes=(3, 4, 6, 3), **kw)


def resnet_v2_152(**kw) -> ResNetV2:
    return ResNetV2(stage_sizes=(3, 8, 36, 3), **kw)


def make_inference_fn(model: ResNetV2, image_size: int = 224,
                      batch: int = 8) -> Tuple[Any, Any]:
    """(jitted_fn, example_args) for quota-enforced inference benchmarks."""
    key = jax.random.PRNGKey(0)
    x = jnp.ones((batch, image_size, image_size, 3), jnp.float32)
    variables = model.init(key, x, train=False)

    @jax.jit
    def infer(variables, x):
        return model.apply(variables, x, train=False)

    return infer, (variables, x)
