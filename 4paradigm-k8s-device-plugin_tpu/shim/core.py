"""ctypes bindings for the native vtpucore shared-region library.

Every consumer of the cross-process accounting state goes through here: the
Python shim's CPU-backend enforcement, the runtime broker's per-tenant
quotas, the vtpu-smi monitor.  The native library itself is the contract —
see native/vtpucore/vtpu_core.h for semantics (reference analogue:
src/multiprocess/multiprocess_memory_limit.c in vgpu/libvgpu.so).
"""

from __future__ import annotations

import ctypes
import os
import threading
import time
from typing import List, Optional, Sequence

from ..utils.envspec import MAX_DEVICES_PER_NODE

_SEARCH_PATHS = (
    os.environ.get("VTPU_CORE_LIB", ""),
    # container-side mount injected at Allocate
    "/usr/local/vtpu/libvtpucore.so",
    # repo build tree (tests / dev)
    os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "native", "build", "libvtpucore.so"),
)


class DeviceStats(ctypes.Structure):
    # Mirror of native vtpu_device_stats (vtpu_core.h).  Layout drift
    # against the C struct is machine-checked by the vtpu-wmm atomics
    # checker (`mirror:` declarations in the vtpu_core.h ground-truth
    # block) — field order, offsets and sizes must all agree.
    _fields_ = [
        ("limit_bytes", ctypes.c_uint64),
        ("used_bytes", ctypes.c_uint64),
        ("peak_bytes", ctypes.c_uint64),
        ("core_limit_pct", ctypes.c_int32),
        ("n_procs", ctypes.c_int32),
        ("busy_us", ctypes.c_uint64),
    ]


class ProcStats(ctypes.Structure):
    # Mirror of native vtpu_proc_stats (vtpu_core.h); drift-checked —
    # see DeviceStats.
    _fields_ = [
        ("pid", ctypes.c_int),
        ("host_pid", ctypes.c_int),
        ("used_bytes", ctypes.c_uint64 * MAX_DEVICES_PER_NODE),
        # per-device cumulative device time (us) — per-tenant duty cycle
        ("busy_us", ctypes.c_uint64 * MAX_DEVICES_PER_NODE),
    ]


# Mirror of VTPU_MAX_PROCS (vtpu_core.h); drift-checked by the
# vtpu-wmm atomics checker's `mirror-const:` declaration.
MAX_PROCS = 64


class TraceEvent(ctypes.Structure):
    """Mirror of native vtpu_trace_event (vtpu_core.h); drift-checked
    — see DeviceStats."""

    _fields_ = [
        ("t_ns", ctypes.c_uint64),
        ("kind", ctypes.c_uint32),
        ("dev", ctypes.c_uint32),
        ("value", ctypes.c_uint64),
        ("arg", ctypes.c_uint64),
    ]


# Event kinds (vtpu_core.h enum) — the shim/interposer hot-path events.
TEV_RATE_WAIT = 1   # token-bucket block: value=waited us, arg=cost us
TEV_MEM_STALL = 2   # mem_acquire refused: value=bytes, arg=limit
TEV_DISPATCH = 3
TEV_USER = 16


class ExecDesc(ctypes.Structure):
    """Mirror of native ExecDesc (vtpu_core.h) — one vtpu-fastlane
    execute descriptor; drift-checked like DeviceStats (the `mirror:`
    row in the vtpu_core.h ground-truth block)."""

    _fields_ = [
        ("eseq", ctypes.c_uint64),
        ("route", ctypes.c_uint64),
        ("arg_off", ctypes.c_uint64),
        ("arg_len", ctypes.c_uint64),
        ("cost_us", ctypes.c_uint64),
        ("t_sub_ns", ctypes.c_uint64),
        ("eflags", ctypes.c_uint64),
        ("status", ctypes.c_int64),
        ("actual_us", ctypes.c_uint64),
        ("t_done_ns", ctypes.c_uint64),
    ]


# ExecDesc.status values (vtpu_core.h VTPU_EXEC_*).
EXEC_OK = 0
EXEC_ENOTFOUND = -1
EXEC_EINTERNAL = -2
EXEC_ECANCELED = -3

# ExecRing gate word (vtpu_core.h VTPU_EXEC_GATE_*): non-zero tells the
# producer to fall back to the brokered socket path.
GATE_OPEN = 0
GATE_PARKED = 1
GATE_CLOSED = 2

TEV_NAMES = {TEV_RATE_WAIT: "rate_wait", TEV_MEM_STALL: "mem_stall",
             TEV_DISPATCH: "dispatch"}


def _find_lib() -> str:
    for p in _SEARCH_PATHS:
        if p and os.path.exists(p):
            return p
    raise FileNotFoundError(
        "libvtpucore.so not found (build with `make -C native` or set "
        "VTPU_CORE_LIB)")


_lib: Optional[ctypes.CDLL] = None


def load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    lib = ctypes.CDLL(_find_lib())
    lib.vtpu_region_open.restype = ctypes.c_void_p
    lib.vtpu_region_open.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                     ctypes.POINTER(ctypes.c_uint64),
                                     ctypes.POINTER(ctypes.c_int32)]
    lib.vtpu_region_close.argtypes = [ctypes.c_void_p]
    lib.vtpu_proc_register.restype = ctypes.c_int
    lib.vtpu_proc_register.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.vtpu_proc_deregister.argtypes = [ctypes.c_void_p]
    lib.vtpu_sweep_dead.restype = ctypes.c_int
    lib.vtpu_sweep_dead.argtypes = [ctypes.c_void_p]
    lib.vtpu_sweep_dead_host.restype = ctypes.c_int
    lib.vtpu_sweep_dead_host.argtypes = [ctypes.c_void_p]
    lib.vtpu_mem_acquire.restype = ctypes.c_int
    lib.vtpu_mem_acquire.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                     ctypes.c_uint64, ctypes.c_int]
    lib.vtpu_mem_acquire_capped.restype = ctypes.c_int
    lib.vtpu_mem_acquire_capped.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_uint64, ctypes.c_uint64]
    lib.vtpu_mem_release.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                     ctypes.c_uint64]
    lib.vtpu_mem_info.restype = ctypes.c_int
    lib.vtpu_mem_info.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                  ctypes.POINTER(ctypes.c_uint64),
                                  ctypes.POINTER(ctypes.c_uint64)]
    lib.vtpu_device_get_stats.restype = ctypes.c_int
    lib.vtpu_device_get_stats.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                          ctypes.POINTER(DeviceStats)]
    lib.vtpu_proc_get_stats.restype = ctypes.c_int
    lib.vtpu_proc_get_stats.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                        ctypes.POINTER(ProcStats)]
    lib.vtpu_rate_acquire.restype = ctypes.c_uint64
    lib.vtpu_rate_acquire.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                      ctypes.c_uint64, ctypes.c_int]
    lib.vtpu_rate_adjust.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                     ctypes.c_int64]
    lib.vtpu_rate_block.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                    ctypes.c_uint64, ctypes.c_int]
    lib.vtpu_set_core_limit.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                        ctypes.c_int32]
    lib.vtpu_region_set_wc.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.vtpu_set_mem_limit.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                       ctypes.c_uint64]
    lib.vtpu_reset_slot.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.vtpu_busy_add.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                  ctypes.c_uint64]
    lib.vtpu_region_ndevices.restype = ctypes.c_int
    lib.vtpu_region_ndevices.argtypes = [ctypes.c_void_p]
    # -- trace event ring (vtpu-trace) --
    # A host-mounted libvtpucore.so can be OLDER than this shim
    # (daemonset upgrade skew, explicitly supported elsewhere): missing
    # trace symbols must degrade to tracing-unavailable, never break
    # quota enforcement wholesale.
    try:
        lib.vtpu_trace_open.restype = ctypes.c_void_p
        lib.vtpu_trace_open.argtypes = [ctypes.c_char_p, ctypes.c_uint32]
        lib.vtpu_trace_close.argtypes = [ctypes.c_void_p]
        lib.vtpu_trace_emit.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                        ctypes.c_uint32, ctypes.c_uint64,
                                        ctypes.c_uint64]
        lib.vtpu_trace_head.restype = ctypes.c_uint64
        lib.vtpu_trace_head.argtypes = [ctypes.c_void_p]
        lib.vtpu_trace_capacity.restype = ctypes.c_uint32
        lib.vtpu_trace_capacity.argtypes = [ctypes.c_void_p]
        lib.vtpu_trace_read.restype = ctypes.c_int
        lib.vtpu_trace_read.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                        ctypes.POINTER(TraceEvent),
                                        ctypes.c_int,
                                        ctypes.POINTER(ctypes.c_uint64)]
        lib.vtpu_region_trace_ring.restype = ctypes.c_void_p
        lib.vtpu_region_trace_ring.argtypes = [ctypes.c_void_p]
        lib.vtpu_rate_level.restype = ctypes.c_int64
        lib.vtpu_rate_level.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib._vtpu_has_trace = True
    except AttributeError:
        lib._vtpu_has_trace = False
    # -- vtpu-fastlane execute ring --
    # Same upgrade-skew contract as the trace symbols: an old mounted
    # libvtpucore.so degrades to fastlane-unavailable (the client stays
    # on the brokered path), never breaks enforcement.
    try:
        lib.vtpu_exec_open.restype = ctypes.c_void_p
        lib.vtpu_exec_open.argtypes = [ctypes.c_char_p, ctypes.c_uint32]
        lib.vtpu_exec_close.argtypes = [ctypes.c_void_p]
        lib.vtpu_exec_submit.restype = ctypes.c_int
        lib.vtpu_exec_submit.argtypes = [ctypes.c_void_p,
                                         ctypes.POINTER(ExecDesc)]
        lib.vtpu_exec_submit_batch.restype = ctypes.c_int
        lib.vtpu_exec_submit_batch.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ExecDesc), ctypes.c_int]
        lib.vtpu_exec_take.restype = ctypes.c_int
        lib.vtpu_exec_take.argtypes = [ctypes.c_void_p,
                                       ctypes.POINTER(ExecDesc),
                                       ctypes.c_int]
        lib.vtpu_exec_complete.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64,
            ctypes.c_int]
        lib.vtpu_exec_completions.restype = ctypes.c_int
        lib.vtpu_exec_completions.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.POINTER(ExecDesc),
            ctypes.c_int]
        lib.vtpu_exec_tail.restype = ctypes.c_uint64
        lib.vtpu_exec_tail.argtypes = [ctypes.c_void_p]
        lib.vtpu_exec_headc.restype = ctypes.c_uint64
        lib.vtpu_exec_headc.argtypes = [ctypes.c_void_p]
        lib.vtpu_exec_capacity.restype = ctypes.c_uint32
        lib.vtpu_exec_capacity.argtypes = [ctypes.c_void_p]
        lib.vtpu_exec_credits.restype = ctypes.c_int64
        lib.vtpu_exec_credits.argtypes = [ctypes.c_void_p]
        lib.vtpu_exec_wait_headc.restype = ctypes.c_int
        lib.vtpu_exec_wait_headc.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_uint64]
        lib.vtpu_exec_wait_tail.restype = ctypes.c_int
        lib.vtpu_exec_wait_tail.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_uint64]
        lib.vtpu_exec_gate_set.argtypes = [ctypes.c_void_p,
                                           ctypes.c_uint32]
        lib.vtpu_exec_gate.restype = ctypes.c_uint32
        lib.vtpu_exec_gate.argtypes = [ctypes.c_void_p]
        lib.vtpu_exec_credit_mint.restype = ctypes.c_int
        lib.vtpu_exec_credit_mint.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64]
        lib.vtpu_exec_credit_spend.restype = ctypes.c_int
        lib.vtpu_exec_credit_spend.argtypes = [ctypes.c_void_p,
                                               ctypes.c_int64]
        lib.vtpu_exec_credit_level.restype = ctypes.c_int64
        lib.vtpu_exec_credit_level.argtypes = [ctypes.c_void_p]
        lib._vtpu_has_exec = True
    except AttributeError:
        lib._vtpu_has_exec = False
    # -- multi-chip completion vector (vtpu-fastlane-everywhere) --
    # Newer than the base exec-ring symbols: a mounted libvtpucore.so
    # with rings but no cvec degrades multi-chip lanes to the brokered
    # path (single-chip fastlane keeps working).
    try:
        lib.vtpu_exec_cvec_set.argtypes = [ctypes.c_void_p,
                                           ctypes.c_uint32,
                                           ctypes.c_uint64]
        lib.vtpu_exec_cvec_get.restype = ctypes.c_uint64
        lib.vtpu_exec_cvec_get.argtypes = [ctypes.c_void_p,
                                           ctypes.c_uint32]
        lib.vtpu_exec_cvec_min.restype = ctypes.c_uint64
        lib.vtpu_exec_cvec_min.argtypes = [ctypes.c_void_p,
                                           ctypes.c_uint32]
        lib.vtpu_exec_cvec_wait.restype = ctypes.c_int
        lib.vtpu_exec_cvec_wait.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint64,
            ctypes.c_uint64, ctypes.c_uint64]
        lib._vtpu_has_cvec = True
    except AttributeError:
        lib._vtpu_has_cvec = False
    lib.vtpu_region_active_procs.restype = ctypes.c_int
    lib.vtpu_region_active_procs.argtypes = [ctypes.c_void_p]
    lib.vtpu_core_version.restype = ctypes.c_char_p
    lib._vtpu_fast = _load_fast()
    _lib = lib
    return lib


def _load_fast() -> Optional[ctypes.PyDLL]:
    """GIL-holding twin of the hot region atomics (docs/PERF.md).

    A CDLL call releases the GIL and must re-acquire it on return; for
    the sub-µs accounting atomics the broker issues several times per
    execute, that round trip — measured at tens of µs under thread
    contention, pure gil_drop_request latency — dwarfs the native work.
    PyDLL skips the release.  The functions bound here only ever take
    the region's ROBUST mutex for nanosecond-scale critical sections
    (EOWNERDEAD-safe, so a crashed holder cannot wedge a waiter);
    anything that sleeps (rate_block) or does syscalls stays on the
    GIL-releasing CDLL.  ``VTPU_NOGIL_ATOMICS=0`` opts out."""
    if os.environ.get("VTPU_NOGIL_ATOMICS", "1") == "0":
        return None
    try:
        fast = ctypes.PyDLL(_find_lib())
    except OSError:
        return None
    fast.vtpu_mem_acquire.restype = ctypes.c_int
    fast.vtpu_mem_acquire.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                      ctypes.c_uint64, ctypes.c_int]
    fast.vtpu_mem_acquire_capped.restype = ctypes.c_int
    fast.vtpu_mem_acquire_capped.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_uint64, ctypes.c_uint64]
    fast.vtpu_mem_release.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                      ctypes.c_uint64]
    fast.vtpu_rate_acquire.restype = ctypes.c_uint64
    fast.vtpu_rate_acquire.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                       ctypes.c_uint64, ctypes.c_int]
    fast.vtpu_rate_adjust.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                      ctypes.c_int64]
    fast.vtpu_busy_add.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                   ctypes.c_uint64]
    # vtpu-fastlane ring hot ops: submit/take/complete/completions
    # never block (the wait helpers stay on the GIL-releasing CDLL),
    # and the handle-local mutexes they take are uncontended
    # nanosecond-scale sections — the PyDLL round-trip saving is the
    # same sub-µs win the accounting atomics get.
    try:
        fast.vtpu_exec_submit.restype = ctypes.c_int
        fast.vtpu_exec_submit.argtypes = [ctypes.c_void_p,
                                          ctypes.POINTER(ExecDesc)]
        fast.vtpu_exec_take.restype = ctypes.c_int
        fast.vtpu_exec_take.argtypes = [ctypes.c_void_p,
                                        ctypes.POINTER(ExecDesc),
                                        ctypes.c_int]
        fast.vtpu_exec_complete.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64,
            ctypes.c_int]
        fast.vtpu_exec_completions.restype = ctypes.c_int
        fast.vtpu_exec_completions.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.POINTER(ExecDesc),
            ctypes.c_int]
        fast.vtpu_exec_tail.restype = ctypes.c_uint64
        fast.vtpu_exec_tail.argtypes = [ctypes.c_void_p]
        fast.vtpu_exec_headc.restype = ctypes.c_uint64
        fast.vtpu_exec_headc.argtypes = [ctypes.c_void_p]
        fast.vtpu_exec_credits.restype = ctypes.c_int64
        fast.vtpu_exec_credits.argtypes = [ctypes.c_void_p]
        fast.vtpu_exec_gate.restype = ctypes.c_uint32
        fast.vtpu_exec_gate.argtypes = [ctypes.c_void_p]
        fast.vtpu_exec_credit_spend.restype = ctypes.c_int
        fast.vtpu_exec_credit_spend.argtypes = [ctypes.c_void_p,
                                                ctypes.c_int64]
        fast.vtpu_exec_credit_level.restype = ctypes.c_int64
        fast.vtpu_exec_credit_level.argtypes = [ctypes.c_void_p]
    except AttributeError:
        pass
    return fast


class SharedRegion:
    """One mmap'd accounting region shared by all processes of a vTPU
    allocation."""

    def __init__(self, path: str, limits: Sequence[int] = (),
                 core_pcts: Sequence[int] = ()):
        self.lib = load()
        n = max(len(limits), len(core_pcts))
        arr_l = (ctypes.c_uint64 * max(n, 1))(*limits) if limits else None
        arr_c = (ctypes.c_int32 * max(n, 1))(*core_pcts) if core_pcts else None
        self.handle = self.lib.vtpu_region_open(
            path.encode(), n, arr_l, arr_c)
        if not self.handle:
            raise OSError(f"vtpu_region_open({path!r}) failed")
        self.path = path
        # Hot accounting atomics go through the GIL-holding PyDLL twin
        # when available (docs/PERF.md; see _load_fast) — pre-bound
        # here so the per-call cost is one attribute lookup.
        fast = getattr(self.lib, "_vtpu_fast", None) or self.lib
        self._c_mem_acquire = fast.vtpu_mem_acquire
        self._c_mem_acquire_capped = fast.vtpu_mem_acquire_capped
        self._c_mem_release = fast.vtpu_mem_release
        self._c_rate_acquire = fast.vtpu_rate_acquire
        self._c_rate_adjust = fast.vtpu_rate_adjust
        self._c_busy_add = fast.vtpu_busy_add

    # -- lifecycle --
    def close(self) -> None:
        if self.handle:
            self.lib.vtpu_region_close(self.handle)
            self.handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def register(self, host_pid: int = 0) -> int:
        return self.lib.vtpu_proc_register(self.handle, host_pid)

    def deregister(self) -> None:
        self.lib.vtpu_proc_deregister(self.handle)

    def sweep_dead(self) -> int:
        return self.lib.vtpu_sweep_dead(self.handle)

    def sweep_dead_host(self) -> int:
        """Host-namespace sweep by host_pid — node monitor only."""
        return self.lib.vtpu_sweep_dead_host(self.handle)

    # -- memory --
    def mem_acquire(self, dev: int, nbytes: int,
                    oversubscribe: bool = False) -> bool:
        return self._c_mem_acquire(self.handle, dev, nbytes,
                                   1 if oversubscribe else 0) == 0

    def mem_acquire_capped(self, dev: int, nbytes: int,
                           cap_bytes: int) -> bool:
        """Admit past the limit up to cap_bytes total, atomically
        (broker overshoot residency)."""
        return self._c_mem_acquire_capped(
            self.handle, dev, nbytes, int(cap_bytes)) == 0

    def mem_release(self, dev: int, nbytes: int) -> None:
        self._c_mem_release(self.handle, dev, nbytes)

    def mem_info(self, dev: int):
        free = ctypes.c_uint64()
        total = ctypes.c_uint64()
        if self.lib.vtpu_mem_info(self.handle, dev, ctypes.byref(free),
                                  ctypes.byref(total)) != 0:
            raise OSError(f"vtpu_mem_info({dev}) failed")
        return free.value, total.value

    def device_stats(self, dev: int) -> DeviceStats:
        out = DeviceStats()
        if self.lib.vtpu_device_get_stats(self.handle, dev,
                                          ctypes.byref(out)) != 0:
            raise OSError(f"vtpu_device_get_stats({dev}) failed")
        return out

    def proc_stats(self) -> List[ProcStats]:
        out = []
        for slot in range(MAX_PROCS):
            st = ProcStats()
            if self.lib.vtpu_proc_get_stats(self.handle, slot,
                                            ctypes.byref(st)) == 0:
                out.append(st)
        return out

    # -- rate limiting --
    def rate_acquire(self, dev: int, cost_us: int, priority: int = 1) -> int:
        """0 = admitted; else nanoseconds to sleep before retry."""
        return self._c_rate_acquire(self.handle, dev, cost_us, priority)

    def rate_block(self, dev: int, cost_us: int, priority: int = 1) -> None:
        self.lib.vtpu_rate_block(self.handle, dev, cost_us, priority)

    def rate_adjust(self, dev: int, delta_us: int) -> None:
        self._c_rate_adjust(self.handle, dev, delta_us)

    def set_core_limit(self, dev: int, pct: int) -> None:
        self.lib.vtpu_set_core_limit(self.handle, dev, pct)

    def set_work_conserving(self, on: bool) -> None:
        """Idle-share redistribution across device entries — broker
        regions only (entries = tenant slots of ONE chip); see
        vtpu_core.h."""
        self.lib.vtpu_region_set_wc(self.handle, 1 if on else 0)

    def set_mem_limit(self, dev: int, limit_bytes: int) -> None:
        """Re-seed one slot's HBM cap (broker per-grant quotas)."""
        self.lib.vtpu_set_mem_limit(self.handle, dev, int(limit_bytes))

    def reset_slot(self, dev: int) -> None:
        """Reset a recycled tenant slot's bucket/busy counters."""
        self.lib.vtpu_reset_slot(self.handle, dev)

    def busy_add(self, dev: int, us: int) -> None:
        """Record completed device time (duty-cycle source)."""
        self._c_busy_add(self.handle, dev, int(us))

    def rate_level(self, dev: int) -> int:
        """Current token-bucket level (us; negative = borrowed) — the
        slow-op watchdog's "bucket level" context field.  0 when the
        mounted library predates vtpu-trace."""
        if not getattr(self.lib, "_vtpu_has_trace", False):
            return 0
        return int(self.lib.vtpu_rate_level(self.handle, dev))

    def trace_ring(self) -> "Optional[TraceRing]":
        """The per-process event ring auto-attached at open when
        VTPU_TRACE is set (native emits rate waits / mem stalls into
        it); None when tracing is off or the library predates it."""
        if not getattr(self.lib, "_vtpu_has_trace", False):
            return None
        h = self.lib.vtpu_region_trace_ring(self.handle)
        return TraceRing._adopt(self.lib, h) if h else None

    @property
    def ndevices(self) -> int:
        return self.lib.vtpu_region_ndevices(self.handle)

    def active_procs(self) -> int:
        """Live registered processes (sweeps dead ones first)."""
        return self.lib.vtpu_region_active_procs(self.handle)


class RateLease:
    """Client-side rate lease over the shared region's token bucket
    (docs/PERF.md): one ``rate_acquire`` pre-debits a µs quantum —
    through the SAME native atomics every co-tenant reads, so fairness
    stays region-owned — and subsequent admissions burn the local
    balance with plain arithmetic instead of a native bucket round
    trip per execute.  Re-syncs when the balance is exhausted, on
    expiry (the unburned remainder refunds via ``rate_adjust`` so an
    idling process cannot park device time), and on ``revoke``.

    The internal lock is ``lease.mu`` in the broker's lock-order
    ground truth: it may wrap region bucket calls (lease.mu >
    region.lock) but the *blocking* fallback path always runs with the
    lock released."""

    def __init__(self, region: SharedRegion, dev: int = 0,
                 quantum_us: Optional[int] = None,
                 ttl_s: Optional[float] = None):
        self.mu = threading.Lock()
        self.region = region
        self.dev = dev
        if quantum_us is None:
            quantum_us = int(os.environ.get("VTPU_RATE_LEASE_US",
                                            "20000") or 0)
        self.quantum_us = max(int(quantum_us), 0)
        # A few quanta of wall time: long enough to amortize, short
        # enough that a stalled process returns its pre-debit quickly.
        self.ttl_s = (ttl_s if ttl_s is not None
                      else max(4.0 * self.quantum_us / 1e6, 0.05))
        self._us = 0.0
        self._exp = 0.0
        self.grants = 0
        self.refunds = 0

    def acquire(self, cost_us: float, priority: int = 1) -> None:
        """Admit ``cost_us`` of device time, blocking in the native
        bucket only when neither the local balance nor a fresh quantum
        can fund it — the common case is one float decrement."""
        cost = max(int(cost_us), 0)
        if self.quantum_us <= 0:
            self.region.rate_block(self.dev, cost, priority)
            return
        with self.mu:
            now = time.monotonic()
            if self._us > 0.0 and now >= self._exp:
                self._refund_locked()
            if self._us >= cost:
                self._us -= cost
                return
            wait_ns = self.region.rate_acquire(
                self.dev, cost + self.quantum_us, priority)
            if wait_ns == 0:
                self._us += self.quantum_us
                self._exp = now + self.ttl_s
                self.grants += 1
                return
            # Bucket can't fund a whole quantum: fall back to the
            # exact ask (minus whatever balance remains) and BLOCK
            # outside the lock — a throttled process must not hold
            # the lease lock while it waits out its debt.
            need = max(cost - int(self._us), 1)
            self._us = 0.0
        self.region.rate_block(self.dev, need, priority)

    def remaining_us(self) -> float:
        """Unexpired local balance (observability)."""
        with self.mu:
            if time.monotonic() >= self._exp:
                return 0.0
            return self._us

    def revoke(self) -> None:
        """Refund the unburned balance to the bucket immediately
        (broker revoke flag, suspend, process teardown)."""
        with self.mu:
            self._refund_locked()

    def _refund_locked(self) -> None:
        left = int(self._us)
        self._us = 0.0
        self._exp = 0.0
        if left > 0:
            self.refunds += 1
            self.region.rate_adjust(self.dev, -left)


class ExecRing:
    """vtpu-fastlane SPSC execute ring (native/vtpucore): one producer
    (the tenant client/interposer), one consumer (the broker's fastlane
    drainer), a credit admission gate, a broker-published fallback gate
    and the burst-credit bank words — all over the exact memory orders
    the vtpu_core.h ground-truth block declares.  Ring files live next
    to the accounting region (``<region>.lane<slot>.ring``)."""

    def __init__(self, path: str, entries: int = 0):
        self.lib = load()
        if not getattr(self.lib, "_vtpu_has_exec", False):
            raise OSError(
                "libvtpucore.so predates vtpu-fastlane (no vtpu_exec_* "
                "symbols); redeploy the matching daemonset")
        self.handle = self.lib.vtpu_exec_open(path.encode(),
                                              int(entries))
        if not self.handle:
            raise OSError(f"vtpu_exec_open({path!r}) failed")
        self.path = path
        fast = getattr(self.lib, "_vtpu_fast", None)
        if fast is None or not hasattr(fast, "vtpu_exec_submit"):
            fast = self.lib
        self._c_submit = fast.vtpu_exec_submit
        self._c_take = fast.vtpu_exec_take
        self._c_complete = fast.vtpu_exec_complete
        self._c_completions = fast.vtpu_exec_completions
        self._c_tail = fast.vtpu_exec_tail
        self._c_headc = fast.vtpu_exec_headc
        self._c_credits = fast.vtpu_exec_credits
        self._c_gate = fast.vtpu_exec_gate
        self._c_credit_spend = fast.vtpu_exec_credit_spend
        self._c_credit_level = fast.vtpu_exec_credit_level
        # Reused scratch buffers (take/completions are hot-path calls;
        # per-call ctypes array construction would dominate).
        self._buf_n = 256
        self._buf = (ExecDesc * self._buf_n)()
        self._st = (ctypes.c_int64 * self._buf_n)()
        self._ac = (ctypes.c_uint64 * self._buf_n)()
        # numpy views over the scratch (vtpu-fastlane bulk paths: one
        # vectorized pass instead of per-descriptor ctypes attribute
        # walks).  Lazy import: shim.core itself must stay numpy-free
        # for minimal consumers.
        try:
            import numpy as _np
            self._buf_np = _np.frombuffer(
                self._buf, dtype=_np.uint64).reshape(self._buf_n, 10)
            self._st_np = _np.frombuffer(self._st, dtype=_np.int64)
            self._ac_np = _np.frombuffer(self._ac, dtype=_np.uint64)
        except ImportError:
            self._buf_np = self._st_np = self._ac_np = None

    def close(self) -> None:
        if self.handle:
            self.lib.vtpu_exec_close(self.handle)
            self.handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _h(self):
        """Live handle, or raise.  The native entry points tolerate a
        NULL handle with benign defaults (gate() reads 0 = GATE_OPEN,
        submit refuses) — exactly the combination that silently spins
        a producer on a stale closed lane, so closed-ring operations
        fail loudly instead (ConnectionError: the lane is gone; the
        caller's normal reconnect/fallback machinery applies)."""
        h = self.handle
        if not h:
            raise ConnectionError("ExecRing is closed")
        return h

    # -- producer ----------------------------------------------------------

    def submit(self, desc: ExecDesc) -> bool:
        """Publish one descriptor; False = credit/slot gate refused
        (back-pressure: drain completions, retry)."""
        return self._c_submit(self._h(), ctypes.byref(desc)) == 0

    def completions(self, from_seq: int, max_n: int = 0):
        """Completed descriptors [from_seq, headc), up to max_n — the
        returned list aliases an internal scratch buffer, consume it
        before the next call."""
        n = min(max_n or self._buf_n, self._buf_n)
        got = self._c_completions(self._h(), int(from_seq),
                                  self._buf, n)
        return [self._buf[i] for i in range(max(got, 0))]

    def wait_headc(self, seq: int, timeout_s: float,
                   spin_us: int = 100) -> bool:
        return self.lib.vtpu_exec_wait_headc(
            self._h(), int(seq), int(max(timeout_s, 0.0) * 1e9),
            int(spin_us) * 1000) == 1

    # -- consumer ----------------------------------------------------------

    def take(self, max_n: int = 0):
        """Peek up to max_n submitted-but-untaken descriptors (headc
        does NOT advance until complete()); aliases scratch."""
        n = min(max_n or self._buf_n, self._buf_n)
        got = self._c_take(self._h(), self._buf, n)
        return [self._buf[i] for i in range(max(got, 0))]

    def take_np(self, max_n: int = 0):
        """Bulk peek: (count, uint64 ndarray view [count, 10] over the
        scratch — columns are the ExecDesc fields in declaration
        order).  Valid until the next take; None view when numpy is
        unavailable."""
        if self._buf_np is None:
            return 0, None
        n = min(max_n or self._buf_n, self._buf_n)
        got = self._c_take(self._h(), self._buf, n)
        if got <= 0:
            return 0, None
        return got, self._buf_np[:got]

    def submit_batch(self, descs, n: int) -> int:
        """Publish up to n descriptors from a ctypes ExecDesc array in
        ONE native call; returns the count admitted (stops at the
        first credit/slot refusal)."""
        return int(self.lib.vtpu_exec_submit_batch(
            self._h(), descs, int(n)))

    def complete_np(self, st_np, ac_np, t_done_ns: int, n: int) -> None:
        """Vectorized complete: caller filled the first n entries of
        the scratch status/actual views (``scratch_views``)."""
        self._c_complete(self._h(), self._st, self._ac,
                         int(t_done_ns), int(n))

    def scratch_views(self):
        """(status int64 view, actual uint64 view) for complete_np."""
        return self._st_np, self._ac_np

    def complete(self, statuses, actuals, t_done_ns: int) -> None:
        """Complete the n oldest taken descriptors (publishes headc
        once, returns the credits with one RMW)."""
        n = min(len(statuses), self._buf_n)
        for i in range(n):
            self._st[i] = int(statuses[i])
            self._ac[i] = int(actuals[i])
        self._c_complete(self._h(), self._st, self._ac,
                         int(t_done_ns), n)

    def wait_tail(self, seq: int, timeout_s: float,
                  spin_us: int = 100) -> bool:
        return self.lib.vtpu_exec_wait_tail(
            self._h(), int(seq), int(max(timeout_s, 0.0) * 1e9),
            int(spin_us) * 1000) == 1

    # -- shared ------------------------------------------------------------

    @property
    def tail(self) -> int:
        return int(self._c_tail(self._h()))

    @property
    def headc(self) -> int:
        return int(self._c_headc(self._h()))

    @property
    def capacity(self) -> int:
        return int(self.lib.vtpu_exec_capacity(self._h()))

    @property
    def credits(self) -> int:
        return int(self._c_credits(self._h()))

    @property
    def depth(self) -> int:
        """Submitted-but-uncompleted descriptors (ring depth)."""
        return max(self.tail - self.headc, 0)

    def gate(self) -> int:
        return int(self._c_gate(self._h()))

    def gate_set(self, v: int) -> None:
        self.lib.vtpu_exec_gate_set(self._h(), int(v))

    def credit_mint(self, us: int, cap_us: int) -> bool:
        return self.lib.vtpu_exec_credit_mint(
            self._h(), int(us), int(cap_us)) == 1

    def credit_spend(self, us: int) -> bool:
        return self._c_credit_spend(self._h(), int(us)) == 1

    def credit_level(self) -> int:
        return int(self._c_credit_level(self._h()))

    # -- multi-chip completion vector (lead ring only) ---------------------

    @property
    def has_cvec(self) -> bool:
        return bool(getattr(self.lib, "_vtpu_has_cvec", False))

    def cvec_set(self, idx: int, seq: int) -> None:
        """Release-publish ordinal ``idx``'s completed sequence count
        (each chip's completer, after its own headc publish)."""
        self.lib.vtpu_exec_cvec_set(self._h(), int(idx), int(seq))

    def cvec_get(self, idx: int) -> int:
        return int(self.lib.vtpu_exec_cvec_get(self._h(), int(idx)))

    def cvec_min(self, n: int) -> int:
        """The join point: min completed sequence over ordinals
        [0, n) — acquire loads, so a joined sequence's side effects
        are visible."""
        return int(self.lib.vtpu_exec_cvec_min(self._h(), int(n)))

    def cvec_wait(self, n: int, seq: int, timeout_s: float,
                  spin_us: int = 100) -> bool:
        return self.lib.vtpu_exec_cvec_wait(
            self._h(), int(n), int(seq),
            int(max(timeout_s, 0.0) * 1e9), int(spin_us) * 1000) == 1


class TraceRing:
    """Lock-free mmap'd per-process trace event ring (vtpu-trace):
    single writer (the owning process), any number of readers.  The
    emitting side makes no syscalls — see native/vtpucore/vtpu_core.h.
    Ring files live next to the accounting region as
    ``<region>.trace.<pid>``."""

    def __init__(self, path: str, size_kb: int = 0):
        self.lib = load()
        if not getattr(self.lib, "_vtpu_has_trace", False):
            raise OSError(
                "libvtpucore.so predates vtpu-trace (no vtpu_trace_* "
                "symbols); redeploy the matching daemonset")
        self.handle = self.lib.vtpu_trace_open(path.encode(),
                                               int(size_kb))
        if not self.handle:
            raise OSError(f"vtpu_trace_open({path!r}) failed")
        self.path = path
        self._owned = True

    @classmethod
    def _adopt(cls, lib, handle) -> "TraceRing":
        """Wrap a region-attached native ring WITHOUT owning it (the
        region close releases it)."""
        self = cls.__new__(cls)
        self.lib = lib
        self.handle = handle
        self.path = ""
        self._owned = False
        return self

    def close(self) -> None:
        if self._owned and self.handle:
            self.lib.vtpu_trace_close(self.handle)
        self.handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def emit(self, kind: int, dev: int = 0, value: int = 0,
             arg: int = 0) -> None:
        self.lib.vtpu_trace_emit(self.handle, int(kind), int(dev),
                                 int(value), int(arg))

    @property
    def head(self) -> int:
        return int(self.lib.vtpu_trace_head(self.handle))

    @property
    def capacity(self) -> int:
        return int(self.lib.vtpu_trace_capacity(self.handle))

    def read(self, cursor: int = 0, max_events: int = 1024):
        """Returns (events, next_cursor); each event is a dict with the
        kind decoded.  Poll with the returned cursor."""
        buf = (TraceEvent * max_events)()
        nxt = ctypes.c_uint64(cursor)
        n = self.lib.vtpu_trace_read(self.handle, int(cursor), buf,
                                     max_events, ctypes.byref(nxt))
        out = []
        for i in range(max(n, 0)):
            ev = buf[i]
            out.append({
                "t_ns": int(ev.t_ns),
                "kind": TEV_NAMES.get(int(ev.kind), str(int(ev.kind))),
                "dev": int(ev.dev),
                "value": int(ev.value),
                "arg": int(ev.arg),
            })
        return out, int(nxt.value)
