"""ctypes bindings for the native vtpucore shared-region library.

Every consumer of the cross-process accounting state goes through here: the
Python shim's CPU-backend enforcement, the runtime broker's per-tenant
quotas, the vtpu-smi monitor.  The native library itself is the contract —
see native/vtpucore/vtpu_core.h for semantics (reference analogue:
src/multiprocess/multiprocess_memory_limit.c in vgpu/libvgpu.so).
"""

from __future__ import annotations

import ctypes
import os
from typing import List, Optional, Sequence

from ..utils.envspec import MAX_DEVICES_PER_NODE

_SEARCH_PATHS = (
    os.environ.get("VTPU_CORE_LIB", ""),
    # container-side mount injected at Allocate
    "/usr/local/vtpu/libvtpucore.so",
    # repo build tree (tests / dev)
    os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "native", "build", "libvtpucore.so"),
)


class DeviceStats(ctypes.Structure):
    _fields_ = [
        ("limit_bytes", ctypes.c_uint64),
        ("used_bytes", ctypes.c_uint64),
        ("peak_bytes", ctypes.c_uint64),
        ("core_limit_pct", ctypes.c_int32),
        ("n_procs", ctypes.c_int32),
        ("busy_us", ctypes.c_uint64),
    ]


class ProcStats(ctypes.Structure):
    _fields_ = [
        ("pid", ctypes.c_int),
        ("host_pid", ctypes.c_int),
        ("used_bytes", ctypes.c_uint64 * MAX_DEVICES_PER_NODE),
        # per-device cumulative device time (us) — per-tenant duty cycle
        ("busy_us", ctypes.c_uint64 * MAX_DEVICES_PER_NODE),
    ]


MAX_PROCS = 64


class TraceEvent(ctypes.Structure):
    """Mirror of native vtpu_trace_event (vtpu_core.h)."""

    _fields_ = [
        ("t_ns", ctypes.c_uint64),
        ("kind", ctypes.c_uint32),
        ("dev", ctypes.c_uint32),
        ("value", ctypes.c_uint64),
        ("arg", ctypes.c_uint64),
    ]


# Event kinds (vtpu_core.h enum) — the shim/interposer hot-path events.
TEV_RATE_WAIT = 1   # token-bucket block: value=waited us, arg=cost us
TEV_MEM_STALL = 2   # mem_acquire refused: value=bytes, arg=limit
TEV_DISPATCH = 3
TEV_USER = 16

TEV_NAMES = {TEV_RATE_WAIT: "rate_wait", TEV_MEM_STALL: "mem_stall",
             TEV_DISPATCH: "dispatch"}


def _find_lib() -> str:
    for p in _SEARCH_PATHS:
        if p and os.path.exists(p):
            return p
    raise FileNotFoundError(
        "libvtpucore.so not found (build with `make -C native` or set "
        "VTPU_CORE_LIB)")


_lib: Optional[ctypes.CDLL] = None


def load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    lib = ctypes.CDLL(_find_lib())
    lib.vtpu_region_open.restype = ctypes.c_void_p
    lib.vtpu_region_open.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                     ctypes.POINTER(ctypes.c_uint64),
                                     ctypes.POINTER(ctypes.c_int32)]
    lib.vtpu_region_close.argtypes = [ctypes.c_void_p]
    lib.vtpu_proc_register.restype = ctypes.c_int
    lib.vtpu_proc_register.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.vtpu_proc_deregister.argtypes = [ctypes.c_void_p]
    lib.vtpu_sweep_dead.restype = ctypes.c_int
    lib.vtpu_sweep_dead.argtypes = [ctypes.c_void_p]
    lib.vtpu_sweep_dead_host.restype = ctypes.c_int
    lib.vtpu_sweep_dead_host.argtypes = [ctypes.c_void_p]
    lib.vtpu_mem_acquire.restype = ctypes.c_int
    lib.vtpu_mem_acquire.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                     ctypes.c_uint64, ctypes.c_int]
    lib.vtpu_mem_acquire_capped.restype = ctypes.c_int
    lib.vtpu_mem_acquire_capped.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_uint64, ctypes.c_uint64]
    lib.vtpu_mem_release.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                     ctypes.c_uint64]
    lib.vtpu_mem_info.restype = ctypes.c_int
    lib.vtpu_mem_info.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                  ctypes.POINTER(ctypes.c_uint64),
                                  ctypes.POINTER(ctypes.c_uint64)]
    lib.vtpu_device_get_stats.restype = ctypes.c_int
    lib.vtpu_device_get_stats.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                          ctypes.POINTER(DeviceStats)]
    lib.vtpu_proc_get_stats.restype = ctypes.c_int
    lib.vtpu_proc_get_stats.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                        ctypes.POINTER(ProcStats)]
    lib.vtpu_rate_acquire.restype = ctypes.c_uint64
    lib.vtpu_rate_acquire.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                      ctypes.c_uint64, ctypes.c_int]
    lib.vtpu_rate_adjust.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                     ctypes.c_int64]
    lib.vtpu_rate_block.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                    ctypes.c_uint64, ctypes.c_int]
    lib.vtpu_set_core_limit.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                        ctypes.c_int32]
    lib.vtpu_region_set_wc.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.vtpu_set_mem_limit.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                       ctypes.c_uint64]
    lib.vtpu_reset_slot.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.vtpu_busy_add.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                  ctypes.c_uint64]
    lib.vtpu_region_ndevices.restype = ctypes.c_int
    lib.vtpu_region_ndevices.argtypes = [ctypes.c_void_p]
    # -- trace event ring (vtpu-trace) --
    # A host-mounted libvtpucore.so can be OLDER than this shim
    # (daemonset upgrade skew, explicitly supported elsewhere): missing
    # trace symbols must degrade to tracing-unavailable, never break
    # quota enforcement wholesale.
    try:
        lib.vtpu_trace_open.restype = ctypes.c_void_p
        lib.vtpu_trace_open.argtypes = [ctypes.c_char_p, ctypes.c_uint32]
        lib.vtpu_trace_close.argtypes = [ctypes.c_void_p]
        lib.vtpu_trace_emit.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                        ctypes.c_uint32, ctypes.c_uint64,
                                        ctypes.c_uint64]
        lib.vtpu_trace_head.restype = ctypes.c_uint64
        lib.vtpu_trace_head.argtypes = [ctypes.c_void_p]
        lib.vtpu_trace_capacity.restype = ctypes.c_uint32
        lib.vtpu_trace_capacity.argtypes = [ctypes.c_void_p]
        lib.vtpu_trace_read.restype = ctypes.c_int
        lib.vtpu_trace_read.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                        ctypes.POINTER(TraceEvent),
                                        ctypes.c_int,
                                        ctypes.POINTER(ctypes.c_uint64)]
        lib.vtpu_region_trace_ring.restype = ctypes.c_void_p
        lib.vtpu_region_trace_ring.argtypes = [ctypes.c_void_p]
        lib.vtpu_rate_level.restype = ctypes.c_int64
        lib.vtpu_rate_level.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib._vtpu_has_trace = True
    except AttributeError:
        lib._vtpu_has_trace = False
    lib.vtpu_region_active_procs.restype = ctypes.c_int
    lib.vtpu_region_active_procs.argtypes = [ctypes.c_void_p]
    lib.vtpu_core_version.restype = ctypes.c_char_p
    _lib = lib
    return lib


class SharedRegion:
    """One mmap'd accounting region shared by all processes of a vTPU
    allocation."""

    def __init__(self, path: str, limits: Sequence[int] = (),
                 core_pcts: Sequence[int] = ()):
        self.lib = load()
        n = max(len(limits), len(core_pcts))
        arr_l = (ctypes.c_uint64 * max(n, 1))(*limits) if limits else None
        arr_c = (ctypes.c_int32 * max(n, 1))(*core_pcts) if core_pcts else None
        self.handle = self.lib.vtpu_region_open(
            path.encode(), n, arr_l, arr_c)
        if not self.handle:
            raise OSError(f"vtpu_region_open({path!r}) failed")
        self.path = path

    # -- lifecycle --
    def close(self) -> None:
        if self.handle:
            self.lib.vtpu_region_close(self.handle)
            self.handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def register(self, host_pid: int = 0) -> int:
        return self.lib.vtpu_proc_register(self.handle, host_pid)

    def deregister(self) -> None:
        self.lib.vtpu_proc_deregister(self.handle)

    def sweep_dead(self) -> int:
        return self.lib.vtpu_sweep_dead(self.handle)

    def sweep_dead_host(self) -> int:
        """Host-namespace sweep by host_pid — node monitor only."""
        return self.lib.vtpu_sweep_dead_host(self.handle)

    # -- memory --
    def mem_acquire(self, dev: int, nbytes: int,
                    oversubscribe: bool = False) -> bool:
        return self.lib.vtpu_mem_acquire(self.handle, dev, nbytes,
                                         1 if oversubscribe else 0) == 0

    def mem_acquire_capped(self, dev: int, nbytes: int,
                           cap_bytes: int) -> bool:
        """Admit past the limit up to cap_bytes total, atomically
        (broker overshoot residency)."""
        return self.lib.vtpu_mem_acquire_capped(
            self.handle, dev, nbytes, int(cap_bytes)) == 0

    def mem_release(self, dev: int, nbytes: int) -> None:
        self.lib.vtpu_mem_release(self.handle, dev, nbytes)

    def mem_info(self, dev: int):
        free = ctypes.c_uint64()
        total = ctypes.c_uint64()
        if self.lib.vtpu_mem_info(self.handle, dev, ctypes.byref(free),
                                  ctypes.byref(total)) != 0:
            raise OSError(f"vtpu_mem_info({dev}) failed")
        return free.value, total.value

    def device_stats(self, dev: int) -> DeviceStats:
        out = DeviceStats()
        if self.lib.vtpu_device_get_stats(self.handle, dev,
                                          ctypes.byref(out)) != 0:
            raise OSError(f"vtpu_device_get_stats({dev}) failed")
        return out

    def proc_stats(self) -> List[ProcStats]:
        out = []
        for slot in range(MAX_PROCS):
            st = ProcStats()
            if self.lib.vtpu_proc_get_stats(self.handle, slot,
                                            ctypes.byref(st)) == 0:
                out.append(st)
        return out

    # -- rate limiting --
    def rate_acquire(self, dev: int, cost_us: int, priority: int = 1) -> int:
        """0 = admitted; else nanoseconds to sleep before retry."""
        return self.lib.vtpu_rate_acquire(self.handle, dev, cost_us,
                                          priority)

    def rate_block(self, dev: int, cost_us: int, priority: int = 1) -> None:
        self.lib.vtpu_rate_block(self.handle, dev, cost_us, priority)

    def rate_adjust(self, dev: int, delta_us: int) -> None:
        self.lib.vtpu_rate_adjust(self.handle, dev, delta_us)

    def set_core_limit(self, dev: int, pct: int) -> None:
        self.lib.vtpu_set_core_limit(self.handle, dev, pct)

    def set_work_conserving(self, on: bool) -> None:
        """Idle-share redistribution across device entries — broker
        regions only (entries = tenant slots of ONE chip); see
        vtpu_core.h."""
        self.lib.vtpu_region_set_wc(self.handle, 1 if on else 0)

    def set_mem_limit(self, dev: int, limit_bytes: int) -> None:
        """Re-seed one slot's HBM cap (broker per-grant quotas)."""
        self.lib.vtpu_set_mem_limit(self.handle, dev, int(limit_bytes))

    def reset_slot(self, dev: int) -> None:
        """Reset a recycled tenant slot's bucket/busy counters."""
        self.lib.vtpu_reset_slot(self.handle, dev)

    def busy_add(self, dev: int, us: int) -> None:
        """Record completed device time (duty-cycle source)."""
        self.lib.vtpu_busy_add(self.handle, dev, int(us))

    def rate_level(self, dev: int) -> int:
        """Current token-bucket level (us; negative = borrowed) — the
        slow-op watchdog's "bucket level" context field.  0 when the
        mounted library predates vtpu-trace."""
        if not getattr(self.lib, "_vtpu_has_trace", False):
            return 0
        return int(self.lib.vtpu_rate_level(self.handle, dev))

    def trace_ring(self) -> "Optional[TraceRing]":
        """The per-process event ring auto-attached at open when
        VTPU_TRACE is set (native emits rate waits / mem stalls into
        it); None when tracing is off or the library predates it."""
        if not getattr(self.lib, "_vtpu_has_trace", False):
            return None
        h = self.lib.vtpu_region_trace_ring(self.handle)
        return TraceRing._adopt(self.lib, h) if h else None

    @property
    def ndevices(self) -> int:
        return self.lib.vtpu_region_ndevices(self.handle)

    def active_procs(self) -> int:
        """Live registered processes (sweeps dead ones first)."""
        return self.lib.vtpu_region_active_procs(self.handle)


class TraceRing:
    """Lock-free mmap'd per-process trace event ring (vtpu-trace):
    single writer (the owning process), any number of readers.  The
    emitting side makes no syscalls — see native/vtpucore/vtpu_core.h.
    Ring files live next to the accounting region as
    ``<region>.trace.<pid>``."""

    def __init__(self, path: str, size_kb: int = 0):
        self.lib = load()
        if not getattr(self.lib, "_vtpu_has_trace", False):
            raise OSError(
                "libvtpucore.so predates vtpu-trace (no vtpu_trace_* "
                "symbols); redeploy the matching daemonset")
        self.handle = self.lib.vtpu_trace_open(path.encode(),
                                               int(size_kb))
        if not self.handle:
            raise OSError(f"vtpu_trace_open({path!r}) failed")
        self.path = path
        self._owned = True

    @classmethod
    def _adopt(cls, lib, handle) -> "TraceRing":
        """Wrap a region-attached native ring WITHOUT owning it (the
        region close releases it)."""
        self = cls.__new__(cls)
        self.lib = lib
        self.handle = handle
        self.path = ""
        self._owned = False
        return self

    def close(self) -> None:
        if self._owned and self.handle:
            self.lib.vtpu_trace_close(self.handle)
        self.handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def emit(self, kind: int, dev: int = 0, value: int = 0,
             arg: int = 0) -> None:
        self.lib.vtpu_trace_emit(self.handle, int(kind), int(dev),
                                 int(value), int(arg))

    @property
    def head(self) -> int:
        return int(self.lib.vtpu_trace_head(self.handle))

    @property
    def capacity(self) -> int:
        return int(self.lib.vtpu_trace_capacity(self.handle))

    def read(self, cursor: int = 0, max_events: int = 1024):
        """Returns (events, next_cursor); each event is a dict with the
        kind decoded.  Poll with the returned cursor."""
        buf = (TraceEvent * max_events)()
        nxt = ctypes.c_uint64(cursor)
        n = self.lib.vtpu_trace_read(self.handle, int(cursor), buf,
                                     max_events, ctypes.byref(nxt))
        out = []
        for i in range(max(n, 0)):
            ev = buf[i]
            out.append({
                "t_ns": int(ev.t_ns),
                "kind": TEV_NAMES.get(int(ev.kind), str(int(ev.kind))),
                "dev": int(ev.dev),
                "value": int(ev.value),
                "arg": int(ev.arg),
            })
        return out, int(nxt.value)
