"""In-container preload bootstrap — the Python half of the injection story.

The device plugin mounts the staged shim directory into every allocated
container and points ``PYTHONPATH`` at it (plugin/server.py Allocate), so
the interpreter imports this module before any user code — the Python
analogue of the reference's ``/etc/ld.so.preload`` mount (reference
server.go:511-515, vgpu/ld.so.preload).

Responsibilities:
  - restore any PYTHONPATH the container image had (ours replaced it; the
    original is recoverable from /proc/1/environ),
  - run the vtpu shim bootstrap (native interposer env wiring),
  - on non-TPU backends, install the pure-Python enforcement.

Never raises: a broken shim must not take down user containers.
"""

import os
import sys

_SHIM_DIR = os.path.dirname(os.path.abspath(__file__))


def _restore_pythonpath():
    try:
        with open("/proc/1/environ", "rb") as f:
            env1 = f.read().split(b"\0")
        for entry in env1:
            if entry.startswith(b"PYTHONPATH="):
                orig = entry.split(b"=", 1)[1].decode()
                for p in orig.split(os.pathsep):
                    if p and p != _SHIM_DIR and p not in sys.path:
                        sys.path.append(p)
                current = os.environ.get("PYTHONPATH", "")
                if orig and orig not in current:
                    os.environ["PYTHONPATH"] = current + os.pathsep + orig
                break
    except OSError:
        pass


def _main():
    _restore_pythonpath()
    if _SHIM_DIR not in sys.path:
        sys.path.insert(0, _SHIM_DIR)
    try:
        from vtpu.shim import pyshim
    except ImportError:
        # Staged copy keeps the package next to this file.
        return
    pyshim.bootstrap()
    platforms = os.environ.get("JAX_PLATFORMS", "")
    try:
        from vtpu.utils.envspec import quota_from_env
        has_quota = bool(quota_from_env().hbm_limit_bytes
                         or quota_from_env().core_limit_pct)
    except Exception:  # noqa: BLE001 - malformed env must not kill startup
        has_quota = False
    if os.environ.get("VTPU_FORCE_PY_ENFORCEMENT") == "1" or (
            platforms == "cpu" and has_quota):
        # Defer until jax is importable *and* quota env exists; swallow
        # everything — user workloads must start regardless.
        try:
            pyshim.install_py_enforcement()
        except Exception as e:  # noqa: BLE001
            print(f"[vtpu shim] enforcement install failed: {e}",
                  file=sys.stderr)


try:
    _main()
except Exception as _e:  # noqa: BLE001
    print(f"[vtpu shim] bootstrap failed: {_e}", file=sys.stderr)
