"""In-container preload bootstrap — the Python half of the injection story.

The device plugin mounts the staged shim directory into every allocated
container and points ``PYTHONPATH`` at it (plugin/server.py Allocate), so
the interpreter imports this module before any user code — the Python
analogue of the reference's ``/etc/ld.so.preload`` mount (reference
server.go:511-515, vgpu/ld.so.preload).

Responsibilities:
  - run the vtpu shim bootstrap (native interposer env wiring),
  - on non-TPU backends, install the pure-Python enforcement.

Known limitation (documented in docs/FLAGS.md): the device plugin's env
injection REPLACES any ``PYTHONPATH`` the image set via Dockerfile ENV —
the kubelet merges plugin envs over image envs at container creation, so
the image's value is unrecoverable here (pid 1 already sees ours).
``VTPU_EXTRA_PYTHONPATH`` set on the pod spec composes: its entries are
appended to sys.path below.  PYTHONPATH set at *runtime* (shell, pod env)
is unaffected because the kubelet applies pod-spec envs after plugin envs.

Never raises: a broken shim must not take down user containers.
"""

import os
import sys

_SHIM_DIR = os.path.dirname(os.path.abspath(__file__))


def _insert_extra_paths():
    """VTPU_EXTRA_PYTHONPATH entries go to the FRONT of sys.path (after
    the shim dir), preserving normal PYTHONPATH precedence over
    site-packages — an image that shadowed an installed package keeps
    shadowing it."""
    extra = os.environ.get("VTPU_EXTRA_PYTHONPATH", "")
    at = 1
    for p in extra.split(os.pathsep):
        if p and p not in sys.path:
            sys.path.insert(at, p)
            at += 1


def _warn_pythonpath_merge():
    """One visible line when Allocate MERGED a user-declared PYTHONPATH
    behind the shim entry (plugin/server.py): the user's entries are
    live, but positioned after ours — say so in-container instead of
    leaving the reordering silent.  Gated on the explicit merge flag the
    plugin sets alongside the merge: PYTHONPATH entries added at runtime
    or via Dockerfile ENV are not a merge and must not trigger it."""
    if os.environ.get("VTPU_PYTHONPATH_MERGED") != "1":
        return
    shim_pp = os.environ.get("VTPU_SHIM_PYTHONPATH", _SHIM_DIR)
    merged = [p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
              if p and os.path.abspath(p) != os.path.abspath(shim_pp)]
    if merged:
        print("[vtpu shim] PYTHONPATH merged: kept "
              f"{os.pathsep.join(merged)} after the vTPU shim entry "
              "(docs/FLAGS.md)", file=sys.stderr)


def _main():
    if _SHIM_DIR not in sys.path:
        sys.path.insert(0, _SHIM_DIR)
    _insert_extra_paths()
    _warn_pythonpath_merge()
    try:
        from vtpu.shim import pyshim
    except ImportError:
        # Staged copy keeps the package next to this file.
        return
    pyshim.bootstrap()
    # vtpu-metricsd (docs/METRICSD.md): serve the virtualized libtpu
    # MetricService so a stock in-container `tpu-info` sees only the
    # grant.  Port-bind race makes this a per-container singleton; any
    # failure is swallowed — metrics must never break user startup.
    if os.environ.get("VTPU_METRICSD_PORT"):
        try:
            from vtpu.metricsd import server as _metricsd
            _metricsd.maybe_start_in_container()
        except Exception as e:  # noqa: BLE001
            print(f"[vtpu shim] metricsd start failed: {e}",
                  file=sys.stderr)
    # Transparent broker bridge (shim/bridge.py): a time-shared grant
    # carries VTPU_RUNTIME_SOCKET — route plain `import jax` workloads
    # through the broker.  The local backend is pinned to CPU so this
    # process can never take the libtpu chip lock away from the broker
    # (the whole point of brokered co-tenancy).  VTPU_BRIDGE=0 opts out.
    bridge_on = bool(os.environ.get("VTPU_RUNTIME_SOCKET")) and \
        os.environ.get("VTPU_BRIDGE", "1") != "0"
    if bridge_on:
        os.environ["JAX_PLATFORMS"] = "cpu"
        # Multi-chip grants: give the local CPU backend as many virtual
        # devices as the grant has chips, so the workload's own
        # mesh/pjit code traces unchanged — the broker maps the exported
        # shardings onto the real granted chips (runtime/server.py
        # tenant_program).
        try:
            n_chips = len([t for t in os.environ.get(
                "TPU_VISIBLE_CHIPS",
                os.environ.get("VTPU_VISIBLE_DEVICES", "")
            ).replace(",", " ").split() if t])
            flags = os.environ.get("XLA_FLAGS", "")
            if n_chips > 1 and \
                    "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count="
                    + str(n_chips)).strip()
        except Exception:  # noqa: BLE001 - cosmetic; single device works
            pass
        try:
            from vtpu.shim import bridge
            bridge.install_import_hook()
        except Exception as e:  # noqa: BLE001 - never break user startup
            print(f"[vtpu shim] bridge hook failed: {e}", file=sys.stderr)
            # Fail CLOSED on enforcement: with the hook dead this
            # process will run on the (already pinned) CPU backend —
            # let the pure-Python enforcement below pick the quotas up
            # rather than running a time-shared grant unenforced.
            bridge_on = False
    platforms = os.environ.get("JAX_PLATFORMS", "")
    try:
        from vtpu.utils.envspec import quota_from_env
        has_quota = bool(quota_from_env().hbm_limit_bytes
                         or quota_from_env().core_limit_pct)
    except Exception:  # noqa: BLE001 - malformed env must not kill startup
        has_quota = False
    # Under the bridge the BROKER enforces quotas (HELLO carries the
    # grant); local py-enforcement would double-charge host-side staging
    # against the same region.
    if not bridge_on and (
            os.environ.get("VTPU_FORCE_PY_ENFORCEMENT") == "1" or (
            platforms == "cpu" and has_quota)):
        # Defer until jax is importable *and* quota env exists; swallow
        # everything — user workloads must start regardless.
        try:
            pyshim.install_py_enforcement()
        except Exception as e:  # noqa: BLE001
            print(f"[vtpu shim] enforcement install failed: {e}",
                  file=sys.stderr)


try:
    _main()
except Exception as _e:  # noqa: BLE001
    print(f"[vtpu shim] bootstrap failed: {_e}", file=sys.stderr)
