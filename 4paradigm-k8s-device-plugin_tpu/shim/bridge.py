"""Transparent broker bridge: unmodified JAX workloads in a time-shared
vTPU grant execute through the node broker — no ``RuntimeClient`` code in
the workload.

The reference's defining property is enforcement inside *unmodified*
containers (reference server.go:511-522 injects everything; the app just
runs CUDA).  On TPU, time-shared co-tenancy runs through the runtime
broker (libtpu admits one process per chip), and until this module the
broker was opt-in: tenants had to code against
``vtpu.runtime.client.RuntimeClient``.  The bridge closes that gap at the
Python layer:

  - ``sitecustomize`` (already injected into every allocated container via
    the PYTHONPATH mount) sees ``VTPU_RUNTIME_SOCKET`` and installs a
    post-import hook;
  - when the workload imports jax, the hook pins the local backend to CPU
    (the process must never take the chip lock) and patches ``jax.jit``,
    ``jax.device_put`` and ``jax.block_until_ready``;
  - a patched jit call traces/lowers LOCALLY (tracing needs no TPU: the
    CPU backend abstract-evals any jittable function), ships the
    ``jax.export`` artifact once per signature, and relays executes over
    the existing runtime protocol.  Results come back as lazy
    ``BridgeArray`` handles, so ``params = step(params, batch)`` loops
    keep every tensor device-resident — no per-step host round trips.

Why Python-level rather than a PJRT C-API relay: JAX workloads are Python
by definition, the jit boundary is THE stable public seam (the PJRT C API
surface jax touches is ~10x larger and churns), and the broker protocol
already speaks jax.export artifacts.  Non-jit eager ops run on the local
CPU backend — numerically identical, and they never touch the chip, so
enforcement cannot be bypassed by skipping jit.

Pipelining: execute replies are consumed lazily (the broker replies at
dispatch; FIFO per connection), so a pure ``state = step(state, ...)``
loop issues one async message per step and never blocks on the
transport.  Dead handles are freed in batches that ride on the next
execute message ("free" field) — zero extra round trips.

Failure contract: if the broker restarts (``VtpuStateLost``), every
handle is poisoned and the error surfaces on the next fetch/step — same
epoch semantics as the cooperative client.  When the broker's state
journal recovered the tenant instead (``VtpuConnectionLost`` with
``resumed=True``, docs/BROKER_RECOVERY.md), the bridge retries the
interrupted send once: journaled arrays and programs survived the
crash, so a loop whose inputs are PUTs keeps running.  If a function
cannot be exported (exotic primitives, non-array leaves), the call
falls back to the local CPU backend — still quota-safe, since the
process holds no chip.
"""

from __future__ import annotations

import collections
import itertools
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils import logging as log

__all__ = ["BridgeArray", "bridge_enabled", "install", "install_import_hook",
           "get_bridge", "reset_for_tests"]

# Client-side cap on unconsumed execute replies.  The broker throttles its
# reader at MAX_PENDING_REPLIES=128; staying well below keeps our sends
# from ever blocking in the socket buffer.
_MAX_OUTSTANDING = 64
# Force a batch-DELETE flush when this many dead handles are pending and a
# synchronous request happens anyway (normally frees ride on executes).
_FLUSH_FREE_AT = 512


def bridge_enabled() -> bool:
    return bool(os.environ.get("VTPU_RUNTIME_SOCKET")) and \
        os.environ.get("VTPU_BRIDGE", "1") != "0"


# ---------------------------------------------------------------------------
# Lazy array handle
# ---------------------------------------------------------------------------


class BridgeArray:
    """Handle to a tenant-owned array living in the broker.

    Duck-types the read-side of a jax array: ``shape``/``dtype``/
    ``__array__``/``__jax_array__``/``block_until_ready`` plus arithmetic
    dunders that fetch and fall back to numpy.  Passing one into a
    bridged jit call reuses the remote buffer directly (device-resident
    across steps); anything else (printing, ``float()``, eager jnp ops)
    fetches once and caches.
    """

    __slots__ = ("_bridge", "_id", "shape", "_dtype", "_np", "_err",
                 "__weakref__")

    def __init__(self, bridge: "Bridge", aid: str, shape, dtype):
        self._bridge = bridge
        self._id = aid
        self.shape = tuple(shape)
        self._dtype = np.dtype(dtype)
        self._np: Optional[np.ndarray] = None
        self._err: Optional[BaseException] = None

    # -- metadata (no fetch) --
    @property
    def dtype(self):
        return self._dtype

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def size(self) -> int:
        n = 1
        for s in self.shape:
            n *= s
        return n

    @property
    def nbytes(self) -> int:
        return self.size * self._dtype.itemsize

    # -- materialisation --
    def _fetch(self) -> np.ndarray:
        if self._err is not None:
            raise RuntimeError(
                f"vtpu bridge: handle {self._id} is poisoned"
            ) from self._err
        if self._np is None:
            self._cache_value(self._bridge.get(self._id))
        return self._np

    def block_until_ready(self) -> "BridgeArray":
        self._fetch()
        return self

    def __array__(self, dtype=None, copy=None):
        a = self._fetch()
        if dtype is not None:
            a = a.astype(dtype, copy=False)
        return a

    def _cache_value(self, a: np.ndarray) -> np.ndarray:
        # Read-only, like a real jax array's host view: a caller mutating
        # np.asarray(handle) must not silently diverge from the remote
        # buffer that later jit calls reuse by id.
        a.flags.writeable = False
        self._np = a
        return a

    def __jax_array__(self):
        import jax.numpy as jnp
        return jnp.asarray(self._fetch())

    def item(self):
        return self._fetch().item()

    def __float__(self):
        return float(self._fetch())

    def __int__(self):
        return int(self._fetch())

    def __bool__(self):
        return bool(self._fetch())

    def __index__(self):
        return self._fetch().__index__()

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __iter__(self):
        return iter(self._fetch())

    def __getitem__(self, key):
        return self._fetch()[key]

    def __format__(self, spec):
        return format(self._fetch(), spec) if spec \
            else repr(self._fetch())

    def __repr__(self):
        try:
            return f"BridgeArray({self._fetch()!r})"
        except Exception:  # noqa: BLE001 - repr must not raise
            return (f"BridgeArray(id={self._id}, shape={self.shape}, "
                    f"dtype={self._dtype}, unavailable)")

    __hash__ = None  # type: ignore[assignment] - arrays are unhashable

    def __getattr__(self, name):
        # Read-path convenience (.T, .mean, .sum, .astype, .reshape, ...):
        # forward to the fetched numpy array.  Internals live in
        # __slots__/properties, so this only fires for unknown names.
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._fetch(), name)

    def __del__(self):
        b = self._bridge
        if b is not None and self._err is None:
            b.free_later(self._id)


def _arith(name, reflected=False):
    def op(self, other):
        a = self._fetch()
        fn = getattr(a, f"__{'r' if reflected else ''}{name}__")
        if isinstance(other, BridgeArray):
            other = other._fetch()  # noqa: SLF001 - same class
        return fn(other)
    return op


for _n in ("add", "sub", "mul", "truediv", "floordiv", "mod", "pow",
           "matmul", "and", "or", "xor"):
    setattr(BridgeArray, f"__{_n}__", _arith(_n))
    setattr(BridgeArray, f"__r{_n}__", _arith(_n, reflected=True))
for _n in ("eq", "ne", "lt", "le", "gt", "ge"):
    setattr(BridgeArray, f"__{_n}__", _arith(_n))
BridgeArray.__neg__ = lambda self: -self._fetch()  # noqa: E731
BridgeArray.__pos__ = lambda self: +self._fetch()  # noqa: E731
BridgeArray.__abs__ = lambda self: abs(self._fetch())  # noqa: E731


# ---------------------------------------------------------------------------
# The bridge proper
# ---------------------------------------------------------------------------


class Bridge:
    """Owns the RuntimeClient connection, the pipelined-reply queue and
    the deferred-free batch.  All socket traffic is serialized under one
    lock; replies are FIFO per connection, so every synchronous request
    drains outstanding execute replies first (mirror of the broker's own
    ordering contract)."""

    def __init__(self, socket_path: str):
        from ..runtime.client import RuntimeClient
        self._mu = threading.RLock()
        self.client = RuntimeClient(socket_path)
        self._ids = itertools.count()
        # Unconsumed replies, in send order: ("exe", [weakref, ...]) for
        # pipelined executes and ("ack", None) for transient-put acks.
        # WEAK refs on purpose: an output the user already dropped must
        # be freeable (its free rides a later execute) — pinning it
        # until its reply is consumed would make server-side memory grow
        # with the pipeline depth instead of the live working set.  The
        # refs exist only to poison still-held handles on failure.
        self._outstanding: "collections.deque[tuple]" = \
            collections.deque()
        self._free: List[str] = []
        self._closed = False
        # Overload pacing (docs/SCHEDULING.md): when the broker sheds a
        # bridged execute (typed VtpuOverload reply), subsequent sends
        # hold off until this monotonic instant — the bridged train
        # loop backs off around the broker's retry_ms hint instead of
        # hammering a saturated broker.
        self._overload_hold = 0.0

    # -- deferred frees --
    def free_later(self, aid: str) -> None:
        if not self._closed:
            # list.append is atomic under the GIL; flushed under _mu.
            self._free.append(aid)

    def _take_frees(self) -> List[str]:
        out, self._free = self._free, []
        return out

    # -- reply pipeline --
    @staticmethod
    def _poison_batch(batch, err: BaseException) -> None:
        for ref in (batch or ()):
            a = ref()
            if a is not None:
                a._err = err  # noqa: SLF001

    def _recv_one_locked(self) -> None:
        from ..runtime.client import (VtpuConnectionLost, VtpuOverload,
                                      VtpuStateLost)
        kind, batch = self._outstanding.popleft()
        try:
            if kind == "exe":
                self.client.execute_recv()
            else:  # transient-put ack
                self.client.recv_reply()
        except VtpuOverload as e:
            # The broker shed this step: only this batch is poisoned
            # (the typed error surfaces on its fetch), and the pacing
            # hold makes the NEXT sends back off around the broker's
            # hint — bounded, jitter-free here because the broker's
            # shed decision itself already varies with load.
            self._overload_hold = time.monotonic() + \
                max(float(e.retry_ms or 50), 10.0) / 1e3
            self._poison_batch(batch, e)
            raise
        except (VtpuStateLost, VtpuConnectionLost) as e:
            # Connection-level loss: every reply still outstanding died
            # with the old socket — poison this batch AND the rest, or
            # the next drain would block forever on replies the fresh
            # connection will never carry.
            self._poison_batch(batch, e)
            self._poison_all(e)
            raise
        except Exception as e:  # noqa: BLE001 - poison just this batch
            # Application-level error reply (quota, NOT_FOUND, ...) on a
            # live connection: only this batch's outputs are invalid.
            self._poison_batch(batch, e)
            raise

    def _drain_locked(self) -> None:
        while self._outstanding:
            self._recv_one_locked()

    def _poison_all(self, err: BaseException) -> None:
        """Broker restarted: every handle this bridge ever issued is
        dead.  Poison what we still hold (outstanding batches); fetches
        of already-confirmed handles will fail server-side NOT_FOUND."""
        while self._outstanding:
            self._poison_batch(self._outstanding.popleft()[1], err)
        self._free = []

    def _sync_prelude_locked(self) -> None:
        self._drain_locked()
        if len(self._free) >= _FLUSH_FREE_AT:
            self.client.delete_many(self._take_frees())

    # -- data plane --
    def put(self, arr: np.ndarray, aid: Optional[str] = None) -> str:
        with self._mu:
            self._sync_prelude_locked()
            return self.client.put(arr, aid=aid).id

    def put_owned(self, arr: np.ndarray) -> BridgeArray:
        aid = self.put(arr, aid=f"bp{next(self._ids)}")
        return BridgeArray(self, aid, arr.shape, arr.dtype)

    def get(self, aid: str) -> np.ndarray:
        with self._mu:
            self._sync_prelude_locked()
            return self.client.get(aid)

    def compile_blob(self, blob: bytes) -> str:
        with self._mu:
            self._sync_prelude_locked()
            return self.client.compile_blob(blob).id

    def run(self, eid: str, arg_items: Sequence[Tuple[str, Any]],
            out_avals: Sequence[Any]) -> List[BridgeArray]:
        """One bridged execute.  ``arg_items`` entries are ``("id", aid)``
        (reuse a live remote buffer) or ``("put", fixed_id, np_arr)``
        (transient upload, replaced in place on the next call).  Puts are
        synchronous (replies are FIFO); the execute itself is sent
        async — its reply is consumed lazily.

        Bounded reconnect-and-resume: when the broker crashed but its
        journal recovered this tenant (``VtpuConnectionLost`` with
        ``resumed=True`` — the client already re-HELLO'd), the
        outstanding replies are gone but every journaled array/program
        survived, so the send is retried ONCE against the new instance
        instead of failing the training loop."""
        from ..runtime.client import VtpuConnectionLost, VtpuStateLost
        with self._mu:
            try:
                return self._run_locked(eid, arg_items, out_avals)
            except VtpuConnectionLost as e:
                if not getattr(e, "resumed", False):
                    raise
                try:
                    return self._run_locked(eid, arg_items, out_avals)
                except (VtpuStateLost, VtpuConnectionLost) as e2:
                    self._poison_all(e2)
                    raise

    def _run_locked(self, eid: str, arg_items: Sequence[Tuple[str, Any]],
                    out_avals: Sequence[Any]) -> List[BridgeArray]:
        from ..runtime.client import VtpuConnectionLost, VtpuStateLost
        try:
            hold = self._overload_hold - time.monotonic()
            if hold > 0:
                # Shed recently: pace this send (overload backpressure).
                time.sleep(min(hold, 2.0))
            while len(self._outstanding) >= _MAX_OUTSTANDING:
                self._recv_one_locked()
            # Arena arg-feed fast path (docs/PERF.md): the dominant
            # bridged-train-loop shape — resident params + ONE fresh
            # host batch per step — streams the batch through the
            # fastlane tx arena as an offset/len descriptor instead
            # of a socket PUT: no payload bytes on the wire, no
            # per-feed broker re-entry, and the broker-side bind
            # still charges the HBM ledger exactly like the PUT it
            # replaces.  Anything else (multiple transients, no
            # lane, VTPU_ARENA_FEED=0, feed window full) keeps the
            # legacy pipelined-PUT framing below.
            transients = [i for i, it in enumerate(arg_items)
                          if it[0] != "id"]
            if len(transients) == 1 and self.client.feed_capable():
                import weakref
                ti = transients[0]
                _, fid, f_arr = arg_items[ti]
                arg_ids = [it[1] if it[0] == "id" else it[1]
                           for it in arg_items]
                out_ids = [f"bo{next(self._ids)}" for _ in out_avals]
                outs = [BridgeArray(self, oid, av.shape, av.dtype)
                        for oid, av in zip(out_ids, out_avals)]
                frees = self._take_frees()
                if self.client.execute_send_feed(
                        eid, arg_ids, out_ids, np.asarray(f_arr),
                        feed_arg=ti, free=frees):
                    self._outstanding.append(
                        ("exe", [weakref.ref(a) for a in outs]))
                    return outs
                # Feed path refused: restore the frees for the
                # legacy send below (they must not be lost).
                self._free = frees + self._free
            arg_ids = []
            for item in arg_items:
                if item[0] == "id":
                    arg_ids.append(item[1])
                else:
                    # Transient upload rides the pipeline too (acks
                    # are consumed lazily, FIFO): a fresh host batch
                    # per step must not drain the in-flight
                    # executes.  The fixed-id replacement stays safe
                    # server-side: the session drains its own
                    # executes before processing a PUT.
                    _, fid, arr = item
                    # Reply frames this upload will cost: always 1 on
                    # the zero-copy raw framing (docs/PERF.md), one per
                    # chunk on the legacy framing.
                    nparts = self.client.put_parts(arr)
                    if nparts > self.client.MAX_PIPELINED_PUT_PARTS:
                        # Huge transient upload: the pipelined path
                        # would deadlock on its own unread acks —
                        # drain and upload synchronously.
                        self._drain_locked()
                        self.client.put(arr, aid=fid)
                    else:
                        for _ in range(self.client.put_send(arr,
                                                            fid)):
                            self._outstanding.append(("ack", None))
                    arg_ids.append(fid)
            import weakref
            out_ids = [f"bo{next(self._ids)}" for _ in out_avals]
            outs = [BridgeArray(self, oid, av.shape, av.dtype)
                    for oid, av in zip(out_ids, out_avals)]
            self.client.execute_send_ids(eid, arg_ids, out_ids,
                                         free=self._take_frees())
            self._outstanding.append(("exe",
                                      [weakref.ref(a)
                                       for a in outs]))
            return outs
        except (VtpuStateLost, VtpuConnectionLost) as e:
            # SEND-side connection loss (broker died mid-loop): the
            # replies for everything still queued died with the old
            # socket — poison and clear, or every later drain
            # (including the transparent retry's compile) would
            # block forever on replies that will never come.
            self._poison_all(e)
            raise

    def sync(self) -> None:
        with self._mu:
            self._drain_locked()

    def epoch(self):
        return self.client.epoch

    def close(self) -> None:
        with self._mu:
            self._closed = True
            self.client.close()


_bridge: Optional[Bridge] = None
_bridge_mu = threading.Lock()


def get_bridge() -> Optional[Bridge]:
    """The process-wide bridge, connected on first use (the broker may
    come up after the container does)."""
    global _bridge
    if _bridge is not None:
        return _bridge
    if not bridge_enabled():
        return None
    with _bridge_mu:
        if _bridge is None:
            # bridge_enabled() already proved the socket env is set;
            # .get keeps the read on the envspec-auditable path (the
            # analyzer bans raw VTPU_* subscript reads).
            path = os.environ.get("VTPU_RUNTIME_SOCKET", "")
            # The daemon only injects the socket when the broker answered
            # at Allocate, but the pod may start while the broker is
            # mid-respawn (the daemon restarts crashed brokers with
            # backoff) — retry briefly before failing LOUDLY.  No silent
            # local fallback: a time-shared tenant must not run
            # unenforced.
            deadline = time.monotonic() + float(os.environ.get(
                "VTPU_BRIDGE_CONNECT_TIMEOUT", "15"))
            while True:
                try:
                    _bridge = Bridge(path)
                    break
                except (ConnectionError, FileNotFoundError, OSError) as e:
                    if time.monotonic() >= deadline:
                        raise RuntimeError(
                            f"vtpu bridge: runtime broker unreachable on "
                            f"{path} ({e}); this pod holds a time-shared "
                            f"vTPU grant and cannot run without the "
                            f"broker") from e
                    time.sleep(0.25)
            log.info("vtpu bridge connected to %s (tenant %s, chip %d)",
                     path, _bridge.client.tenant, _bridge.client.chip)
        return _bridge


def reset_for_tests() -> None:
    global _bridge, _installed
    with _bridge_mu:
        if _bridge is not None:
            try:
                _bridge.close()
            except Exception:  # noqa: BLE001
                pass
        _bridge = None


# ---------------------------------------------------------------------------
# jit bridging
# ---------------------------------------------------------------------------


class _Compiled:
    __slots__ = ("eid", "blob", "out_avals", "out_tree", "epoch",
                 "transient_live", "seq")

    def __init__(self, eid, blob, out_avals, out_tree, epoch, seq):
        self.eid = eid
        self.blob = blob
        self.out_avals = out_avals
        self.out_tree = out_tree
        self.epoch = epoch
        # Which transient arg slots currently hold a server-side copy
        # (freed when a later call feeds that position a BridgeArray).
        self.transient_live: set = set()
        self.seq = seq


def _static_key(values) -> Any:
    hash(values)  # TypeError for unhashable statics, exactly like jax.jit
    return values


class BridgedFunction:
    """What the patched ``jax.jit`` returns.  Compiles once per
    (tree-structure, avals, statics) signature; falls back to the real
    local jit under tracers (nested jit / grad-of-jit) or when export
    fails."""

    def __init__(self, fun, jit_args: tuple, jit_kwargs: dict):
        self._fun = fun
        self._jit_args = jit_args
        self._jit_kwargs = jit_kwargs
        snums = jit_kwargs.get("static_argnums")
        if snums is None:
            snums = ()
        elif isinstance(snums, int):
            snums = (snums,)
        self._static_argnums = tuple(snums)
        snames = jit_kwargs.get("static_argnames") or ()
        if isinstance(snames, str):
            snames = (snames,)
        self._static_argnames = tuple(snames)
        self._cache: Dict[Any, Any] = {}
        self._real = None
        self._mu = threading.Lock()
        self._seq = itertools.count()
        try:
            self.__name__ = getattr(fun, "__name__", "fn")
            self.__doc__ = getattr(fun, "__doc__", None)
        except (AttributeError, TypeError):
            pass

    # Fallback path: the genuine jitted function on the local backend.
    def _real_fn(self):
        if self._real is None:
            import jax
            real_jit = getattr(jax.jit, "_vtpu_real", jax.jit)
            self._real = real_jit(self._fun, *self._jit_args,
                                  **self._jit_kwargs)
        return self._real

    def __getattr__(self, name):
        # .lower()/.trace()/.eval_shape()/... delegate to the real jit.
        return getattr(self._real_fn(), name)

    def _partition(self, args, kwargs):
        spec = []
        dyn = []
        for i, a in enumerate(args):
            if i in self._static_argnums:
                spec.append(("s", a))
            else:
                spec.append(("d", len(dyn)))
                dyn.append(a)
        kw_dyn, kw_stat = {}, {}
        for k, v in kwargs.items():
            if k in self._static_argnames:
                kw_stat[k] = v
            else:
                kw_dyn[k] = v
        return spec, dyn, kw_dyn, kw_stat

    def __call__(self, *args, **kwargs):
        import jax

        bridge = get_bridge()
        if bridge is None:
            return self._real_fn()(*args, **kwargs)
        spec, dyn, kw_dyn, kw_stat = self._partition(args, kwargs)
        leaves, treedef = jax.tree_util.tree_flatten((dyn, kw_dyn))
        if any(isinstance(x, jax.core.Tracer) for x in leaves):
            # Being traced by an outer transform (grad/vmap/outer jit):
            # inline locally — the OUTER call is what gets bridged.
            return self._real_fn()(*args, **kwargs)
        try:
            canon, avals = self._canonicalize(jax, bridge, leaves)
        except (TypeError, ValueError) as e:
            log.debug("bridge: non-array leaves (%s); local fallback", e)
            return self._real_fn()(*args, **kwargs)
        try:
            statics = _static_key((tuple(x[1] if x[0] == "s" else None
                                         for x in spec),
                                   tuple(sorted(kw_stat.items()))))
        except TypeError:
            # Unhashable static arguments: the real jit raises the
            # canonical jax error for this — don't guess a cache key.
            return self._real_fn()(*args, **kwargs)
        key = (treedef,
               tuple((tuple(a.shape), a.dtype.name) for a in avals),
               statics)
        entry = self._cache.get(key)
        if entry == "local":
            return self._real_fn()(*args, **kwargs)
        if entry is None:
            with self._mu:
                entry = self._cache.get(key)
                if entry is None:
                    try:
                        entry = self._compile(jax, bridge, treedef, avals,
                                              spec, kw_stat)
                    except Exception as e:  # noqa: BLE001 - fall back
                        log.warn("bridge: export of %s failed (%s: %s); "
                                 "running on local cpu backend",
                                 self.__name__, type(e).__name__, e)
                        self._cache[key] = "local"
                        return self._real_fn()(*args, **kwargs)
                    self._cache[key] = entry
        if entry.epoch != bridge.epoch():
            # Broker restarted since this program was registered:
            # re-register from the stored blob (cheap — broker dedups).
            with self._mu:
                if entry.epoch != bridge.epoch():
                    entry.eid = bridge.compile_blob(entry.blob)
                    entry.epoch = bridge.epoch()
                    entry.transient_live.clear()
        arg_items = []
        for i, (leaf, arr) in enumerate(zip(leaves, canon)):
            if arr is None:  # live handle on this bridge (canonicalize)
                arg_items.append(("id", leaf._id))  # noqa: SLF001
                if i in entry.transient_live:
                    # This position's previous transient copy is now
                    # unreachable — free it with the next execute.
                    bridge.free_later(f"t{entry.seq}_{i}")
                    entry.transient_live.discard(i)
            else:
                arg_items.append(("put", f"t{entry.seq}_{i}", arr))
                entry.transient_live.add(i)
        from ..runtime.client import VtpuStateLost
        try:
            outs = bridge.run(entry.eid, arg_items, entry.out_avals)
        except VtpuStateLost:
            if not all(item[0] == "put" for item in arg_items):
                # Some inputs were device-resident handles — their data
                # died with the old broker and cannot be re-fed.
                raise
            # Every input rides in this call: re-register the program on
            # the fresh broker instance and retry once, transparently.
            with self._mu:
                entry.eid = bridge.compile_blob(entry.blob)
                entry.epoch = bridge.epoch()
                entry.transient_live = {i for i in range(len(arg_items))}
            outs = bridge.run(entry.eid, arg_items, entry.out_avals)
        return jax.tree_util.tree_unflatten(entry.out_tree, outs)

    @staticmethod
    def _canonicalize(jax, bridge, leaves):
        """Each dynamic leaf -> (numpy value, or None for a live remote
        handle usable by id) plus its ShapeDtypeStruct, with jit's dtype
        canonicalization (python scalars -> weak 32-bit, f64 -> f32
        unless x64 is on).  A poisoned handle raises here (its _fetch
        carries the original failure); a foreign-bridge handle is
        materialised and re-uploaded."""
        import jax.numpy as jnp
        canon: List[Optional[np.ndarray]] = []
        avals = []
        for leaf in leaves:
            if isinstance(leaf, BridgeArray):
                if leaf._bridge is bridge and leaf._err is None:  # noqa: SLF001
                    canon.append(None)
                    avals.append(jax.ShapeDtypeStruct(leaf.shape,
                                                      leaf.dtype))
                    continue
                leaf = leaf._fetch()  # noqa: SLF001 - raises if poisoned
            arr = np.asarray(jnp.asarray(leaf))
            canon.append(arr)
            avals.append(jax.ShapeDtypeStruct(arr.shape, arr.dtype))
        return canon, avals

    def _compile(self, jax, bridge: Bridge, treedef, avals, spec, kw_stat):
        """Trace+export the flat-calling-convention wrapper and register
        it with the broker (tpu+cpu lowering, same as the cooperative
        client: runtime/client.py compile)."""
        fun = self._fun

        def apply(dyn, kw_dyn):
            cargs = [v if tag == "s" else dyn[v] for tag, v in spec]
            return fun(*cargs, **kw_dyn, **kw_stat)

        import jax.numpy as jnp
        sds_dyn, sds_kw = jax.tree_util.tree_unflatten(treedef, avals)
        out_struct = jax.eval_shape(apply, sds_dyn, sds_kw)
        out_leaves, out_tree = jax.tree_util.tree_flatten(out_struct)
        out_avals = []
        for o in out_leaves:
            if hasattr(o, "shape") and hasattr(o, "dtype"):
                out_avals.append(jax.ShapeDtypeStruct(o.shape, o.dtype))
            else:  # constant leaf (input-independent): jit returns arrays
                a = np.asarray(jnp.asarray(o))
                out_avals.append(jax.ShapeDtypeStruct(a.shape, a.dtype))

        def flat_fn(*flat):
            dyn, kw_dyn = jax.tree_util.tree_unflatten(treedef, flat)
            out = apply(dyn, kw_dyn)
            return tuple(jax.tree_util.tree_leaves(out))

        import jax.export  # noqa: F401 - jax lazy-loads submodules
        real_jit = getattr(jax.jit, "_vtpu_real", jax.jit)
        exported = jax.export.export(
            real_jit(flat_fn), platforms=("cpu", "tpu"))(*avals)
        blob = bytes(exported.serialize())
        eid = bridge.compile_blob(blob)
        return _Compiled(eid, blob, out_avals, out_tree, bridge.epoch(),
                         next(self._seq))


# ---------------------------------------------------------------------------
# Patching + import hook
# ---------------------------------------------------------------------------

_installed = False


def install(jax_module=None) -> bool:
    """Patch jax for bridged execution.  Idempotent; returns True when
    the bridge patches are active."""
    global _installed
    if _installed:
        return True
    if not bridge_enabled():
        return False
    import jax
    if jax_module is None:
        jax_module = jax

    real_jit = jax_module.jit

    def jit(fun=None, *args, **kwargs):
        if fun is None:
            # Keyword-only decorator form: @jax.jit(static_argnums=...)
            def deco(f):
                return BridgedFunction(f, args, kwargs)
            return deco
        return BridgedFunction(fun, args, kwargs)

    jit._vtpu_real = real_jit  # noqa: SLF001 - cooperative clients unwrap
    jit._vtpu_bridge = True  # noqa: SLF001
    jax_module.jit = jit

    real_device_put = jax_module.device_put
    # Reentrancy guard: jnp.asarray's canonicalization path calls
    # jax.device_put INTERNALLY on some jax versions (0.4.x
    # lax_numpy.array) — without the guard the patched device_put
    # recurses through itself until the stack dies.  Inner calls run
    # the REAL device_put on the pinned CPU backend (never the chip).
    _dp_reentry = threading.local()

    def device_put(x, device=None, **kw):
        if getattr(_dp_reentry, "active", False):
            return real_device_put(x, device, **kw)
        bridge = None
        leaves, td = jax_module.tree_util.tree_flatten(x)
        if not any(isinstance(v, jax.core.Tracer) for v in leaves):
            try:
                bridge = get_bridge()
            except Exception as e:  # noqa: BLE001 - broker unreachable
                log.warn("bridge: device_put falling back local: %s", e)
        if bridge is None:
            return real_device_put(x, device, **kw)
        import jax.numpy as jnp
        out = []
        for leaf in leaves:
            if isinstance(leaf, BridgeArray):
                out.append(leaf)
                continue
            _dp_reentry.active = True
            try:
                arr = np.asarray(jnp.asarray(leaf))
            except (TypeError, ValueError):
                return real_device_put(x, device, **kw)
            finally:
                _dp_reentry.active = False
            out.append(bridge.put_owned(arr))
        return jax_module.tree_util.tree_unflatten(td, out)

    device_put._vtpu_real = real_device_put  # noqa: SLF001
    jax_module.device_put = device_put

    real_block = jax_module.block_until_ready

    def block_until_ready(x):
        leaves = jax_module.tree_util.tree_leaves(x)
        bridged = [v for v in leaves if isinstance(v, BridgeArray)]
        for v in bridged:
            v.block_until_ready()
        if not bridged:
            return real_block(x)
        # Mixed tree: the non-bridge leaves still owe a real block.
        rest = [v for v in leaves if not isinstance(v, BridgeArray)]
        if rest:
            real_block(rest)
        return x

    block_until_ready._vtpu_real = real_block  # noqa: SLF001
    jax_module.block_until_ready = block_until_ready

    _installed = True
    log.info("vtpu bridge installed: jax.jit executes via %s",
             os.environ.get("VTPU_RUNTIME_SOCKET"))
    return True


class _JaxPostImportHook:
    """Meta-path finder that patches jax right after its first import —
    the shim must not import jax itself (sitecustomize runs in every
    python process of the container, jax or not)."""

    def __init__(self):
        self._busy = False

    def find_spec(self, fullname, path=None, target=None):
        if fullname != "jax" or self._busy:
            return None
        import importlib.util
        self._busy = True
        try:
            spec = importlib.util.find_spec("jax")
        finally:
            self._busy = False
        if spec is None or spec.loader is None:
            return None
        spec.loader = _WrappedLoader(spec.loader)
        return spec


class _WrappedLoader:
    def __init__(self, inner):
        self._inner = inner

    def create_module(self, spec):
        return self._inner.create_module(spec)

    def exec_module(self, module):
        self._inner.exec_module(module)
        try:
            install(module)
        except Exception as e:  # noqa: BLE001 - never break user jax
            log.warn("vtpu bridge install failed: %s; falling back to "
                     "local python enforcement", e)
            # Fail closed: jax is imported and unbridged — install the
            # pure-Python quota enforcement so the grant's limits still
            # apply on the pinned CPU backend.
            try:
                from . import pyshim
                pyshim.install_py_enforcement()
            except Exception as e2:  # noqa: BLE001
                log.warn("python enforcement fallback failed too: %s", e2)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def install_import_hook() -> None:
    """Arrange for install() to run when jax is imported (or now, if it
    already was).  Called by sitecustomize in bridge mode."""
    import sys
    if "jax" in sys.modules:
        install(sys.modules["jax"])
        return
    if not any(isinstance(f, _JaxPostImportHook) for f in sys.meta_path):
        sys.meta_path.insert(0, _JaxPostImportHook())
