#!/usr/bin/env python3
"""In-container quota view — the tenant-side half of vtpu-smi.

The reference makes in-container ``nvidia-smi`` show the quota-adjusted
view through its NVML shim (SURVEY §2.9f); on TPU there is no vendor CLI
to shim, so the daemon mounts THIS script as ``/usr/local/vtpu/vtpu-smi``
into every allocated container (plugin/server.py Allocate, the analogue
of the reference's extra-binary mount at server.go:518-519).  An
operator shelled into a tenant pod runs it to answer "what is my grant,
what am I using, how throttled am I":

  - the Allocate-time env contract (ordinals, chip ids, HBM caps, core
    pct, policy, oversubscribe);
  - live usage/duty from the pod's shared accounting region (interposer
    or py-enforcement path);
  - the broker's view of this pod's tenants when the grant is brokered
    (VTPU_RUNTIME_SOCKET present).

Self-contained: bootstraps imports from its own staged directory; never
writes to the region (opens without registering) and exits 0 even with
no grant env (prints "no vTPU grant").
"""

import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
# Candidate package roots, most specific first: the staged shim dir
# next to this file (in-repo layout), the mounted shim dir below the
# mount point (in-container: this file is /usr/local/vtpu/vtpu-smi and
# the package lives at /usr/local/vtpu/shim/vtpu), and the repo root
# two levels up (in-repo alias package).  The CLI must work from a
# clean `kubectl exec` shell with NO PYTHONPATH.
for _cand in (_HERE, os.path.join(_HERE, "shim"),
              os.path.dirname(os.path.dirname(_HERE))):
    if os.path.isdir(os.path.join(_cand, "vtpu")) \
            and _cand not in sys.path:
        sys.path.insert(0, _cand)
        break
else:
    if _HERE not in sys.path:
        sys.path.insert(0, _HERE)


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024.0
    return f"{n}B"


def _grant_lines(spec) -> list:
    dev_map = os.environ.get("VTPU_DEVICE_MAP", "")
    entries = [tok.split(":", 1) for tok in dev_map.split() if ":" in tok]
    lines = []
    for i, (ordinal, chip) in enumerate(entries or [("0", "?")]):
        cap = spec.limit_for(i)
        lines.append((int(ordinal), chip,
                      _fmt_bytes(cap) if cap else "unlimited"))
    return lines


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv

    try:
        from vtpu.utils import envspec
        spec = envspec.quota_from_env()
    except Exception as e:  # noqa: BLE001 - report, don't crash a shell
        print(f"vtpu-smi: cannot parse grant env: {e}", file=sys.stderr)
        return 1

    has_grant = bool(spec.hbm_limit_bytes or spec.core_limit_pct
                     or spec.visible_devices
                     or os.environ.get("VTPU_DEVICE_MAP"))
    out = {"grant": has_grant}
    if not has_grant:
        if as_json:
            print(json.dumps(out))
        else:
            print("no vTPU grant in this container "
                  "(no VTPU_* env contract)")
        return 0

    out["devices"] = []
    for ordinal, chip, cap in _grant_lines(spec):
        out["devices"].append({"ordinal": ordinal, "chip": chip,
                               "hbm_limit": cap})
    out["core_limit_pct"] = spec.core_limit_pct
    out["policy"] = spec.utilization_policy
    out["oversubscribe"] = bool(spec.oversubscribe)
    out["brokered"] = bool(spec.runtime_socket)

    # Live region view (interposer / py-enforcement path).
    region_path = spec.shared_cache
    if region_path and os.path.exists(region_path):
        try:
            from vtpu.shim.core import SharedRegion
            with SharedRegion(region_path) as reg:
                devs = []
                for d in range(reg.ndevices):
                    st = reg.device_stats(d)
                    devs.append({
                        "device": d,
                        "used": int(st.used_bytes),
                        "limit": int(st.limit_bytes),
                        "peak": int(st.peak_bytes),
                        "core_limit_pct": int(st.core_limit_pct),
                        "busy_us": int(st.busy_us),
                        "procs": int(st.n_procs),
                    })
                out["region"] = devs
        except Exception as e:  # noqa: BLE001
            out["region_error"] = str(e)

    # Recent shim-side stall events (vtpu-trace, VTPU_TRACE=1): this
    # pod's own rate-block waits and memory-acquire refusals from the
    # native per-process rings next to the region — "am I throttled
    # RIGHT NOW, and by what" without broker access.
    if region_path:
        try:
            import glob as _glob

            from vtpu.shim.core import TraceRing
            events = []
            for rp in sorted(_glob.glob(region_path + ".trace.*")):
                try:
                    with TraceRing(rp) as ring:
                        evs, _ = ring.read(0, 4096)
                    events.extend(evs[-32:])
                except OSError:
                    continue
            if events:
                events.sort(key=lambda e: e.get("t_ns", 0))
                out["trace_events"] = events[-16:]
        except Exception:  # noqa: BLE001 - forensics must not break smi
            pass

    # Broker view (time-shared grants).
    if spec.runtime_socket and os.path.exists(spec.runtime_socket):
        try:
            import socket

            from vtpu.runtime import protocol as P
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.settimeout(5.0)
            s.connect(spec.runtime_socket)
            # STATS is bind-free: no tenant slot, no chip binding, no
            # lazy chip claim — a read-only probe must never be able to
            # wedge a claim and take the broker down (ADVICE r5 #2).
            P.send_msg(s, {"kind": P.STATS})
            st = P.recv_msg(s)
            if not st.get("ok") and st.get("code") == "NO_HELLO":
                # Pre-STATS broker (daemonset upgrade skew): fall back
                # to a throwaway HELLO — never under VTPU_TENANT (first
                # HELLO wins the grant seeding), and ALWAYS bound to
                # the grant's own first chip, never default chip 0
                # (binding a foreign chip can lazily claim it).
                chips = os.environ.get("TPU_VISIBLE_CHIPS", "")
                toks = chips.replace(",", " ").split()
                try:
                    dev = int(toks[0]) if toks else 0
                except ValueError:
                    dev = 0
                probe = f"vtpu-smi-probe-{os.getpid()}"
                P.send_msg(s, {"kind": P.HELLO, "tenant": probe,
                               "priority": 1, "device": dev})
                if P.recv_msg(s).get("ok"):
                    P.send_msg(s, {"kind": P.STATS})
                    st = P.recv_msg(s)
            if st.get("ok"):
                out["broker"] = st["tenants"]
                if st.get("journal"):
                    out["broker_journal"] = st["journal"]
            s.close()
        except Exception as e:  # noqa: BLE001
            out["broker_error"] = str(e)

    if as_json:
        print(json.dumps(out, indent=2))
        return 0

    print("vTPU grant")
    for d in out["devices"]:
        print(f"  vtpu {d['ordinal']}: chip {d['chip']}  "
              f"hbm {d['hbm_limit']}")
    print(f"  core limit : {out['core_limit_pct'] or 'unlimited'}"
          f"{'%' if out['core_limit_pct'] else ''}   "
          f"policy {out['policy']}   "
          f"oversubscribe {'on' if out['oversubscribe'] else 'off'}   "
          f"{'brokered' if out['brokered'] else 'interposed'}")
    for d in out.get("region", []):
        pct = (100.0 * d["used"] / d["limit"]) if d["limit"] else 0.0
        print(f"  device {d['device']}: used {_fmt_bytes(d['used'])}"
              f" / {_fmt_bytes(d['limit']) if d['limit'] else 'unl'}"
              f" ({pct:.0f}%)  peak {_fmt_bytes(d['peak'])}  "
              f"busy {d['busy_us'] / 1e6:.1f}s  procs {d['procs']}")
    for name, t in (out.get("broker") or {}).items():
        print(f"  broker tenant {name}: chips {t.get('chips')}  "
              f"used {_fmt_bytes(t['used_bytes'])}"
              f" / {_fmt_bytes(t['limit_bytes']) if t['limit_bytes'] else 'unl'}"
              f"  core {t['core_limit_pct'] or 'unl'}%  "
              f"execs {t['executions']}"
              f"{'  SUSPENDED' if t.get('suspended') else ''}")
    bj = out.get("broker_journal")
    if bj and bj.get("enabled"):
        dropped = (bj.get("tenants_dropped_dead", 0)
                   + bj.get("tenants_dropped_expired", 0))
        print(f"  broker journal: epoch {bj.get('epoch')}  "
              f"recoveries {bj.get('recoveries_total', 0)}  "
              f"readopted {bj.get('tenants_readopted', 0)}  "
              f"dropped {dropped}"
              f"{'  DRAINING' if bj.get('draining') else ''}")
    for ev in out.get("trace_events", []):
        val = (f"{ev['value']}us" if ev["kind"] == "rate_wait"
               else _fmt_bytes(ev["value"]))
        print(f"  stall: {ev['kind']} dev {ev['dev']} {val}")
    if "region_error" in out:
        print(f"  (region unavailable: {out['region_error']})")
    if "broker_error" in out:
        print(f"  (broker unavailable: {out['broker_error']})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
