"""Python-level quota enforcement for JAX processes.

Two jobs:

1. **Native bootstrap** (`bootstrap()`): translate the allocate-time env
   contract into the native injection channel — point ``TPU_LIBRARY_PATH``
   at the PJRT interposer, resolve the real driver for it, translate
   ``VTPU_VISIBLE_DEVICES`` chip UUIDs into ``TPU_VISIBLE_CHIPS`` indices
   via the mounted inventory file.  On TPU nodes this is all that's
   needed; the interposer does the enforcement natively.

2. **Pure-Python fallback** (`install_py_enforcement()`): on backends with
   no wrappable PJRT plugin (notably ``JAX_PLATFORMS=cpu`` in CI) patch
   ``jax.device_put`` and jitted-function dispatch to run the same
   shared-region accounting + token bucket through ctypes.  Quota
   semantics become testable anywhere; the reference has no equivalent
   (its interceptor only works against real CUDA).
"""

from __future__ import annotations

import functools
import os
import time
from typing import Any, Dict, Optional

from ..utils import envspec
from ..utils import logging as log

_installed = False


def _default_interposer() -> Optional[str]:
    cands = [
        os.environ.get("VTPU_INTERPOSER_LIB", ""),
        "/usr/local/vtpu/libvtpu_pjrt.so",
        os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "native", "build",
            "libvtpu_pjrt.so"),
    ]
    for c in cands:
        if c and os.path.exists(c):
            return c
    return None


def _find_real_libtpu() -> Optional[str]:
    real = os.environ.get("VTPU_REAL_LIBTPU")
    if real:
        return real
    try:
        import libtpu  # type: ignore
        p = os.path.join(os.path.dirname(libtpu.__file__), "libtpu.so")
        if os.path.exists(p):
            return p
    except ImportError:
        pass
    for p in ("/lib/libtpu.so", "/usr/lib/libtpu.so"):
        if os.path.exists(p):
            return p
    return None


def _chip_index_map() -> Dict[str, int]:
    """uuid -> node chip index, from the mounted inventory file
    (written by plugin/main.py write_chip_inventory)."""
    path = os.environ.get(envspec.ENV_PCIBUS_FILE)
    out: Dict[str, int] = {}
    if not path or not os.path.exists(path):
        return out
    try:
        with open(path) as f:
            for line in f:
                parts = line.split()
                if len(parts) >= 2:
                    out[parts[1]] = int(parts[0])
    except (OSError, ValueError) as e:
        log.warn("bad chip inventory %s: %s", path, e)
    return out


def bootstrap() -> None:
    """Configure native injection from the env contract.  Idempotent,
    must run before jax imports (sitecustomize guarantees that)."""
    spec = envspec.quota_from_env()
    if not (spec.hbm_limit_bytes or spec.core_limit_pct
            or spec.visible_devices):
        return

    # Chip visibility -> libtpu's own chip filter.
    if spec.visible_devices and "TPU_VISIBLE_CHIPS" not in os.environ:
        idx = _chip_index_map()
        indices = []
        for tok in spec.visible_devices:
            if tok in idx:
                indices.append(str(idx[tok]))
            elif tok.isdigit():
                indices.append(tok)
        if indices:
            os.environ["TPU_VISIBLE_CHIPS"] = ",".join(indices)

    # Native interposer injection (unless the daemon already set it).
    interposer = _default_interposer()
    if interposer and "TPU_LIBRARY_PATH" not in os.environ:
        os.environ["TPU_LIBRARY_PATH"] = interposer
    if interposer and "VTPU_REAL_LIBTPU" not in os.environ:
        real = _find_real_libtpu()
        if real and os.path.realpath(real) != os.path.realpath(interposer):
            os.environ["VTPU_REAL_LIBTPU"] = real

    log.debug("shim bootstrap: limits=%s core=%d%% interposer=%s",
              spec.hbm_limit_bytes, spec.core_limit_pct, interposer)


class _PyEnforcer:
    """Shared-region accounting for the pure-Python path."""

    def __init__(self, spec: envspec.QuotaSpec):
        from .core import SharedRegion
        self.spec = spec
        n = max([o for o in spec.hbm_limit_bytes if o >= 0], default=0) + 1
        n = max(n, 1)
        limits = [spec.limit_for(i) for i in range(n)]
        pcts = [spec.core_limit_pct] * n
        path = spec.shared_cache or "/tmp/vtpushr.cache"
        self.region = SharedRegion(path, limits=limits, core_pcts=pcts)
        self.region.register()
        # Same floor the native interposer honors: keeps throttling
        # meaningful when measured latencies are tiny/unreliable.
        self.min_cost_us = float(os.environ.get("VTPU_MIN_EXEC_COST_US",
                                                "0") or 0)
        # array id -> (dev, nbytes); identity keyed, pruned on __del__ via
        # weakrefs is overkill — jax arrays call block_until_ready paths
        # through us, and tests drive explicit deletes.
        self._cost_ema: Dict[int, float] = {}
        # Contention probe cache for the DEFAULT policy (mirrors the
        # native interposer): sole tenant runs ungated.
        self._contention_at = 0.0
        self._contended = True
        # Per-device rate leases (docs/PERF.md): gate() burns a
        # pre-debited quantum through region atomics instead of a
        # native bucket round trip per execute.  VTPU_RATE_LEASE_US=0
        # restores per-item rate_block.
        self._leases: Dict[int, Any] = {}

    def _lease(self, dev: int):
        lease = self._leases.get(dev)
        if lease is None:
            from .core import RateLease
            lease = self._leases[dev] = RateLease(self.region, dev)
        return lease

    def trace_ring(self):
        """The vtpu-trace per-process event ring (VTPU_TRACE=1), or
        None.  The native layer auto-attaches it at region open and
        emits rate-block waits (gate()) and mem-acquire refusals
        (charge()) into it with no syscalls — this accessor is the
        read side for introspection (vtpu_smi_lite, tests)."""
        return self.region.trace_ring()

    def _gating_active(self) -> bool:
        """Policy switch (reference GPU_CORE_UTILIZATION_POLICY): DISABLE
        never gates, FORCE always, DEFAULT only under contention."""
        policy = self.spec.utilization_policy
        if policy == "DISABLE":
            return False
        if policy == "FORCE":
            return True
        now = time.monotonic()
        if now - self._contention_at > 0.1:
            self._contention_at = now
            self._contended = self.region.active_procs() > 1
        return self._contended

    def clamp_dev(self, dev: int) -> int:
        """Map an ordinal onto the region's device axis (out-of-range →
        0 so a stray id can never fault the accounting)."""
        n = self.region.ndevices
        return dev if 0 <= dev < n else 0

    def charge(self, nbytes: int, dev: int = 0) -> None:
        ok = self.region.mem_acquire(dev, nbytes, self.spec.oversubscribe)
        if not ok:
            free, total = self.region.mem_info(dev)
            if self.spec.active_oom_killer:
                log.error("active OOM killer: quota exceeded on device %d",
                          dev)
                os.kill(os.getpid(), 9)
            raise MemoryError(
                f"RESOURCE_EXHAUSTED: vTPU device {dev} OOM: requested "
                f"{nbytes} bytes, quota {total} (free {free})")

    def release(self, nbytes: int, dev: int = 0) -> None:
        self.region.mem_release(dev, nbytes)

    def gate(self, key: int, dev: int = 0) -> float:
        """Block per the token bucket; returns the cost estimate used
        (negative: ungated, skip the completion-time correction)."""
        est = max(self._cost_ema.get(key, 5000.0), self.min_cost_us)
        if not self._gating_active():
            return -est
        self._lease(dev).acquire(est, self.spec.task_priority)
        return est

    def observe(self, key: int, est: float, actual_us: float,
                dev: int = 0) -> None:
        self.region.busy_add(dev, int(actual_us))
        if est >= 0:
            # Only correct the bucket when the estimate was charged; an
            # ungated run must not bank debt against future co-tenants.
            charged = max(actual_us, self.min_cost_us)
            self.region.rate_adjust(dev, int(charged - est))
        prev = self._cost_ema.get(key)
        self._cost_ema[key] = (actual_us if prev is None
                               else prev * 0.7 + actual_us * 0.3)


_enforcer: Optional[_PyEnforcer] = None


def install_py_enforcement() -> bool:
    """Patch jax.device_put + jitted dispatch with quota checks.  Returns
    True when installed.  Used on CPU/dev backends; real TPU paths use the
    native interposer instead."""
    global _installed, _enforcer
    if _installed:
        return True
    spec = envspec.quota_from_env()
    if not spec.hbm_limit_bytes and not spec.core_limit_pct:
        return False

    import weakref

    import jax
    import numpy as np

    enf = _PyEnforcer(spec)
    _enforcer = enf

    def _leaf_dev(leaf) -> int:
        """Container-visible ordinal of the device actually holding
        `leaf` (VERDICT r2 weak #5: every allocation used to be charged
        to device 0, misaccounting multi-device grants — the native path
        resolves the buffer's device; this is the Python twin)."""
        d = getattr(leaf, "device", None)
        if callable(d):  # older jax: .device() is a method
            try:
                d = d()
            except Exception:  # noqa: BLE001
                d = None
        ds = getattr(d, "device_set", None)
        if ds:
            # Modern jax: .device is a Sharding for multi-device arrays.
            d = min(ds, key=lambda x: x.id)
        if d is None or not hasattr(d, "id"):
            devs = getattr(leaf, "devices", None)
            if callable(devs):
                try:
                    s = devs()
                    d = min(s, key=lambda x: x.id) if s else None
                except Exception:  # noqa: BLE001
                    d = None
        return enf.clamp_dev(int(getattr(d, "id", 0) or 0))

    def _target_dev(device) -> int:
        """Ordinal of a device_put target (Device, Sharding, or None —
        None resolves through jax's default-device config so admission
        is checked against the quota of the device the bytes will
        actually land on (`with jax.default_device(...)` workloads)."""
        if device is None:
            try:
                device = jax.config.jax_default_device
            except AttributeError:
                device = None
        if device is None:
            return 0
        if hasattr(device, "id"):
            return enf.clamp_dev(int(device.id))
        ds = getattr(device, "device_set", None)
        if ds:
            return enf.clamp_dev(min(int(d.id) for d in ds))
        return 0

    def _charge_tracked(out_leaf, nbytes: int, dev: int) -> None:
        """Account an ALREADY-MATERIALISED leaf, releasing when it is
        collected — the lifetime coupling the native interposer gets
        from PJRT_Buffer_Destroy.  Admits unconditionally (oversubscribe
        flag): the transfer passed its admission check on the target
        device before running, and a completed transfer can neither be
        refused nor justify killing the process."""
        enf.region.mem_acquire(dev, nbytes, True)
        try:
            weakref.finalize(out_leaf, enf.release, nbytes, dev)
        except TypeError:
            # Non-weakreferenceable leaf (plain scalar): release now, the
            # charge was only an admission check.
            enf.release(nbytes, dev)

    real_device_put = jax.device_put

    @functools.wraps(real_device_put)
    def device_put(x, device=None, *args, **kwargs):
        sizes = []
        pre_dev = _target_dev(device)
        charged = 0
        try:
            for leaf in jax.tree_util.tree_leaves(x):
                nbytes = getattr(leaf, "nbytes", None)
                if nbytes is None and np.isscalar(leaf):
                    nbytes = 8
                sizes.append(int(nbytes or 0))
                if nbytes:
                    enf.charge(int(nbytes), pre_dev)
                    charged += int(nbytes)
        except BaseException:
            # Mid-pytree admission failure: roll back the earlier
            # leaves' charges or the quota leaks permanently.
            enf.release(charged, pre_dev)
            raise
        try:
            out = real_device_put(x, device, *args, **kwargs)
        except BaseException:
            enf.release(charged, pre_dev)  # transfer failed: no memory
            raise
        # Transfer the charges onto the device-side leaves' lifetimes,
        # re-homed to the device each leaf actually landed on.
        for leaf, nbytes in zip(jax.tree_util.tree_leaves(out), sizes):
            if nbytes:
                enf.release(nbytes, pre_dev)
                _charge_tracked(leaf, nbytes, _leaf_dev(leaf))
        return out

    jax.device_put = device_put

    real_jit = jax.jit

    @functools.wraps(real_jit)
    def jit(fun, *jit_args, **jit_kwargs):
        compiled = real_jit(fun, *jit_args, **jit_kwargs)

        @functools.wraps(compiled)
        def call(*args, **kwargs):
            key = id(compiled)
            est = enf.gate(key)
            t0 = time.monotonic()
            out = compiled(*args, **kwargs)
            out = jax.block_until_ready(out)
            actual_us = (time.monotonic() - t0) * 1e6
            enf.observe(key, est, actual_us)
            for leaf in jax.tree_util.tree_leaves(out):
                nbytes = getattr(leaf, "nbytes", 0)
                if nbytes:
                    # Outputs occupy "device" memory until collected;
                    # admitted with oversubscribe (can't refuse a finished
                    # program), released by finalizer on GC.
                    dev = _leaf_dev(leaf)
                    enf.region.mem_acquire(dev, int(nbytes), True)
                    import weakref

                    try:
                        weakref.finalize(leaf, enf.release, int(nbytes),
                                         dev)
                    except TypeError:
                        enf.release(int(nbytes), dev)
            return out

        call._vtpu_wrapped = True  # noqa: SLF001
        return call

    jax.jit = jit
    _installed = True
    log.info("python quota enforcement installed (limits=%s, core=%d%%)",
             spec.hbm_limit_bytes, spec.core_limit_pct)
    return True


def enforcer() -> Optional["_PyEnforcer"]:
    return _enforcer
