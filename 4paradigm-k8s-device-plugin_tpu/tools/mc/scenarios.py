"""vtpu-mc scenario suite — the small multi-tenant workloads the
interleaving engine explores exhaustively.

Each scenario spawns a handful of MC tasks (tenant clients, an admin
driver) that call the REAL broker entry points — ``TenantSession``
methods, ``AdminSession.handle`` over a scripted socket,
``RuntimeState.tenant/release_tenant`` — against the harness's stub
state.  The dispatcher and metering loops of every chip run as MC
daemon tasks (the patched ``threading.Thread``), so every schedule the
explorer picks is a genuine interleaving of genuine broker code.

Design rule: scenarios are SMALL on purpose.  State-space size is
exponential in concurrent operations; the exhaustive value comes from
covering every interleaving of a few representative transitions
(submit_many + lease grant/burn/refund + expiry + suspend/resume +
tenant crash + journal deferral), not from big workloads.  Add a new
transition class (ROADMAP 3-4: federation, burst credits) as a new
small scenario + a registry invariant, not by growing an existing one.
"""

from __future__ import annotations

from typing import Any, List

from .harness import Harness, fake_program
from .interleave import Scenario
from . import sched as mcsched


def _teardown(h: Harness, sess: Any, t: Any) -> None:
    """The REAL connection-death path (``TenantSession.handle``'s
    finally block): purge still-queued items, drain replies, release
    the tenant, drop its arrays."""
    t.chip.scheduler.purge_session(sess)
    sess._drain()
    if h.state.release_tenant(t):
        sess._cleanup(t)


def _admin_frames(*msgs: dict) -> List[bytes]:
    from ...runtime import protocol as P
    return [P.frame_header(m) for m in msgs]


# ---------------------------------------------------------------------------
# Scenario setups
# ---------------------------------------------------------------------------

def _setup_batch_pipeline(h: Harness, sched: mcsched.Scheduler) -> None:
    """One metered tenant pipelines an EXEC_BATCH whose middle item
    frees an input array at dispatch (deferred journal del), then tears
    down cleanly.  Covers: submit_many, lease grant/burn, zero-RT free,
    journal deferral + pre-reply flush, release refund."""
    sess = h.session()

    def client() -> None:
        t = h.tenant(sess, "A", core_limit=50)
        h.seed_array(t, "w", 64)
        t.executables["p"] = fake_program()
        sess._enqueue_batch(t, {"items": [
            h.exec_spec("p", ["w"], ["o1"]),
            h.exec_spec("p", ["o1"], ["o2"], free=("w",)),
            h.exec_spec("p", ["o2"], ["o3"]),
        ]})
        sess._drain()
        _teardown(h, sess, t)

    sched.spawn(client, "clientA")


def _setup_contention(h: Harness, sched: mcsched.Scheduler) -> None:
    """Two metered tenants race batches through one chip's scheduler:
    the lease-grant/burn paths of both interleave with dispatch and
    retirement.  Covers: concurrent submit_many, round-robin pick,
    per-tenant lease isolation."""
    sA, sB = h.session(), h.session()

    def client(sess: Any, name: str) -> None:
        t = h.tenant(sess, name, core_limit=50)
        t.executables["p"] = fake_program()
        sess._enqueue_batch(t, {"items": [
            h.exec_spec("p", [], ["x1"]),
            h.exec_spec("p", ["x1"], ["x2"]),
        ]})
        sess._drain()
        _teardown(h, sess, t)

    sched.spawn(lambda: client(sA, "A"), "clientA")
    sched.spawn(lambda: client(sB, "B"), "clientB")


def _setup_lease_expiry(h: Harness, sched: mcsched.Scheduler) -> None:
    """A tenant executes, idles past the lease TTL (logical-clock
    jump), then executes again: the second admission must refund the
    expired remainder before re-granting.  Covers: expiry refund,
    re-grant, terminal lease accounting."""
    sess = h.session()

    def client() -> None:
        t = h.tenant(sess, "A", core_limit=50)
        t.executables["p"] = fake_program()
        sess._enqueue_execute(t, h.exec_spec("p", [], ["o1"]))
        sess._drain()
        # Idle past the lease TTL: the logical clock is the scenario's
        # to command (discrete-event style) — no task sleeps.
        h.clock.sleep(4.0 * h.state.rate_lease_ttl_s)
        sess._enqueue_execute(t, h.exec_spec("p", ["o1"], ["o2"]))
        sess._drain()
        _teardown(h, sess, t)

    sched.spawn(client, "clientA")


def _setup_suspend_resume(h: Harness, sched: mcsched.Scheduler) -> None:
    """An admin connection SUSPENDs then RESUMEs tenant A (the REAL
    AdminSession arm over a scripted socket) while A pipelines a batch.
    Covers: suspend lease revoke+refund, queue hold, resume kick (a
    dropped kick is a lost wake), suspend racing bind/dispatch."""
    from ...runtime import protocol as P
    sess = h.session()

    def client() -> None:
        t = h.tenant(sess, "A", core_limit=50)
        t.executables["p"] = fake_program()
        sess._enqueue_batch(t, {"items": [
            h.exec_spec("p", [], ["o1"]),
            h.exec_spec("p", ["o1"], ["o2"]),
        ]})
        sess._drain()
        _teardown(h, sess, t)

    def admin() -> None:
        h.admin(_admin_frames(
            {"kind": P.SUSPEND, "tenant": "A"},
            {"kind": P.RESUME, "tenant": "A"},
        )).handle()

    sched.spawn(client, "clientA")
    sched.spawn(admin, "admin")


def _setup_tenant_crash(h: Harness, sched: mcsched.Scheduler) -> None:
    """Tenant A's connection dies MID-PIPELINE (no drain before the
    teardown path runs): still-queued items are purged and abandoned,
    dispatched ones complete against the dead session, the slot and
    every ledger byte must come back.  An unmetered co-tenant keeps the
    chip busy throughout.  Covers: purge/abandon, batch-slot fill on
    teardown, release refund, close-record ordering."""
    sA, sB = h.session(), h.session()

    def crasher() -> None:
        t = h.tenant(sA, "A", core_limit=50)
        h.seed_array(t, "w", 128)
        t.executables["p"] = fake_program()
        sA._enqueue_batch(t, {"items": [
            h.exec_spec("p", ["w"], ["o1"]),
            h.exec_spec("p", ["o1"], ["o2"], free=("w",)),
            h.exec_spec("p", ["o2"], ["o3"]),
        ]})
        # No drain: the connection is gone — straight to teardown.
        _teardown(h, sA, t)

    def steady() -> None:
        t = h.tenant(sB, "B", core_limit=0)  # unmetered co-tenant
        h.seed_array(t, "wb", 64)
        t.executables["q"] = fake_program()
        # Two SEPARATE executes -> two replies, with a journal-deferred
        # del (free of the journaled array) pending between them: the
        # reply-durability oracle needs exactly this shape to observe a
        # record that was never flushed.
        sB._enqueue_execute(t, h.exec_spec("q", ["wb"], ["y1"],
                                           free=("wb",)))
        sB._drain()
        sB._enqueue_execute(t, h.exec_spec("q", ["y1"], ["y2"]))
        sB._drain()
        _teardown(h, sB, t)

    sched.spawn(crasher, "clientA")
    sched.spawn(steady, "clientB")


def _setup_multichip(h: Harness, sched: mcsched.Scheduler) -> None:
    """A two-chip grant (HELLO devices=[0,1]) executes alongside a
    single-chip tenant on the secondary chip: multi-chip rate debits,
    per-chip ledgers and both chips' dispatchers interleave.  Covers:
    rate_acquire_all partial-refund, per-chip slot accounting,
    cross-chip release."""
    sA, sB = h.session(), h.session()

    def wide() -> None:
        t = h.tenant(sA, "A", core_limit=50, devices=[0, 1])
        t.executables["p"] = fake_program()
        sA._enqueue_batch(t, {"items": [
            h.exec_spec("p", [], ["o1"]),
            h.exec_spec("p", ["o1"], ["o2"]),
        ]})
        sA._drain()
        _teardown(h, sA, t)

    def narrow() -> None:
        t = h.tenant(sB, "B", core_limit=50, device=1)
        t.executables["q"] = fake_program()
        sB._enqueue_execute(t, h.exec_spec("q", [], ["y1"]))
        sB._drain()
        _teardown(h, sB, t)

    sched.spawn(wide, "clientA")
    sched.spawn(narrow, "clientB")


def _setup_churn_rebind(h: Harness, sched: mcsched.Scheduler) -> None:
    """A tenant name releases and immediately rebinds (slot recycle:
    reset_slot must rebase the bucket, the fresh instance must not
    inherit the old lease) while a co-tenant runs.  Covers: slot
    recycle conservation, close/bind journal ordering, lease reclaim
    before recycle."""
    s1, s2, sB = h.session(), h.session(), h.session()

    def churn() -> None:
        t = h.tenant(s1, "A", core_limit=50)
        t.executables["p"] = fake_program()
        s1._enqueue_execute(t, h.exec_spec("p", [], ["o1"]))
        s1._drain()
        _teardown(h, s1, t)
        t2 = h.tenant(s2, "A", core_limit=50)
        t2.executables["p"] = fake_program()
        s2._enqueue_execute(t2, h.exec_spec("p", [], ["o1"]))
        s2._drain()
        _teardown(h, s2, t2)

    def steady() -> None:
        t = h.tenant(sB, "B", core_limit=50)
        t.executables["q"] = fake_program()
        sB._enqueue_execute(t, h.exec_spec("q", [], ["y1"]))
        sB._drain()
        _teardown(h, sB, t)

    sched.spawn(churn, "clientA")
    sched.spawn(steady, "clientB")


def _setup_burst_credits(h: Harness, sched: mcsched.Scheduler) -> None:
    """vtpu-elastic work conservation (docs/SCHEDULING.md): tenant A
    idles long enough to bank credit (one mint at its next submit),
    then bursts a batch whose tail exceeds the frozen bucket's seed —
    the third item must admit FROM THE BANK.  B runs within its own
    bucket throughout.  Covers: idle-window mint, credit-funded
    admission, the token-conservation split (net debit == busy +
    leases - spent credit), credit bounds."""
    sA, sB = h.session(), h.session()

    def burster() -> None:
        t = h.tenant(sA, "A", core_limit=50)
        t.executables["p"] = fake_program()
        # Idle on the logical clock: the mint window is open from bind
        # and closes (banking 0.5s x 50% = 250ms of device time) at
        # the submit below.
        h.clock.sleep(0.5)
        sA._enqueue_batch(t, {"items": [
            h.exec_spec("p", [], ["o1"]),
            h.exec_spec("p", ["o1"], ["o2"]),
            h.exec_spec("p", ["o2"], ["o3"]),
        ]})
        sA._drain()
        _teardown(h, sA, t)

    def steady() -> None:
        t = h.tenant(sB, "B", core_limit=50)
        t.executables["q"] = fake_program()
        sB._enqueue_execute(t, h.exec_spec("q", [], ["y1"]))
        sB._drain()
        _teardown(h, sB, t)

    sched.spawn(burster, "clientA")
    sched.spawn(steady, "clientB")


def _setup_burst_floor(h: Harness, sched: mcsched.Scheduler) -> None:
    """The hard-floor guard under contention (refill bucket): A's
    program costs more than its whole bucket seed, so it can ONLY run
    from banked credit — and B's small bucket throttles it mid-batch,
    so A's spend attempts interleave with a floor-demanding co-tenant.
    Every interleaving must show A spending only while B is NOT
    throttled-with-backlog (floor-under-burst), with A's admission
    eventually succeeding once B drains (no starvation)."""
    sA, sB = h.session(), h.session()

    def burster() -> None:
        t = h.tenant(sA, "A", core_limit=50)
        t.executables["p"] = fake_program()
        # A's learned cost exceeds the bucket seed: bucket admission
        # can never succeed, only the credit bank can fund it.
        t.cost_ema["p"] = 20_000.0
        # Idle long enough to bank the burst (50% x 50ms = 25ms of
        # device time > the 20ms ask) — and SHORT enough that B is
        # still throttled mid-batch when the burst arrives.
        h.clock.sleep(0.05)
        sA._enqueue_execute(t, h.exec_spec("p", [], ["o1"]))
        sA._drain()
        _teardown(h, sA, t)

    def floor() -> None:
        t = h.tenant(sB, "B", core_limit=50)
        t.executables["q"] = fake_program()
        # Pre-drain B's bucket deep into deficit: its batch is then
        # bucket-throttled with backlog for ~60ms of refill — the
        # floor-demand window A's credit burst must NOT cut into.
        t.chip.region.rate_adjust(t.index, 30_000)
        sB._enqueue_batch(t, {"items": [
            h.exec_spec("q", [], ["y1"]),
            h.exec_spec("q", ["y1"], ["y2"]),
            h.exec_spec("q", ["y2"], ["y3"]),
        ]})
        sB._drain()
        _teardown(h, sB, t)

    # B first: the canonical schedules then have B's throttle (the
    # floor-demand signal) registered before A's burst arrives — the
    # deny path of the guard is exercised from schedule one, and the
    # DFS still explores the spend-first orders.
    sched.spawn(floor, "clientB")
    sched.spawn(burster, "clientA")


def _setup_overload_shed(h: Harness, sched: mcsched.Scheduler) -> None:
    """Overload admission control: with a tiny backlog cap, the
    priority-1 tenant's batch must be SHED (typed OVERLOAD results,
    one positional reply) while the priority-0 tenant's work is still
    admitted — lowest priority first, judged by the shed-precedence
    row over the admission oracle log."""
    h.state.admission.max_backlog = 4
    h.state.admission.tenant_cap = 8
    sC, sD = h.session(), h.session()

    def hi() -> None:
        t = h.tenant(sC, "C", priority=0, core_limit=50)
        t.executables["p"] = fake_program()
        sC._enqueue_batch(t, {"items": [
            h.exec_spec("p", [], ["o1"]),
            h.exec_spec("p", ["o1"], ["o2"]),
        ]})
        sC._drain()
        _teardown(h, sC, t)

    def lo() -> None:
        t = h.tenant(sD, "D", priority=1, core_limit=50)
        t.executables["q"] = fake_program()
        # 3 items against a cap of 4: level >= 0.75 > the priority-1
        # shed fraction — refused in EVERY interleaving.
        sD._enqueue_batch(t, {"items": [
            h.exec_spec("q", [], ["y1"]),
            h.exec_spec("q", ["y1"], ["y2"]),
            h.exec_spec("q", ["y2"], ["y3"]),
        ]})
        sD._drain()
        _teardown(h, sD, t)

    sched.spawn(hi, "clientC")
    sched.spawn(lo, "clientD")


def _setup_fastlane_gate(h: Harness, sched: mcsched.Scheduler) -> None:
    """vtpu-fastlane park/RESIZE/release transitions: a tenant's shm
    execute ring (PyRing stand-in, REAL FastlaneHub drain logic) is
    driven through admin SUSPEND, RESUME and RESIZE while descriptors
    sit in it, then the tenant is released and a straggler drain pass
    runs.  The fastlane-park-gate invariant judges the hub's admit
    oracle: no descriptor executes while the tenant is parked or after
    the lane is released."""
    from ...runtime import fastlane as FL
    from ...runtime import protocol as P
    sess = h.session()

    def client() -> None:
        t = h.tenant(sess, "A", core_limit=50)
        prog = fake_program()
        # FASTBIND needs the static out metadata a first brokered
        # dispatch would have filled.
        prog.out_meta = [{"shape": [16], "dtype": "float32",
                          "nbytes": 64}]
        t.executables["p"] = prog
        hub = h.state.fastlane
        ring = FL.PyRing(8)
        lane = FL.BrokerLane(t, ring, None, None, {})
        hub.lanes[t.name] = lane
        t.fastlane = lane
        rep = hub.bind_route(t, "p", [], ["o1"])
        assert rep["ok"], rep
        # Fill the ring FIRST (pre-debiting each estimate through the
        # shared bucket exactly like ClientLane.admit — the drainer's
        # completion-time correction refunds the unused remainder).
        for _ in range(3):
            t.chip.region.rate_acquire(t.index, 100, 1)
            ring.submit(FL.PyDesc(route=0, cost_us=100, t_sub_ns=1))
        # Deterministic park collision (the fastlane-park-ignored
        # selfcheck seed must fire in the DEFAULT schedule, not only
        # deep in the DFS): the client drives the REAL admin SUSPEND
        # arm itself, then drains INTO the park with a loaded ring —
        # the gate must admit nothing.
        h.admin(_admin_frames(
            {"kind": P.SUSPEND, "tenant": "A"},
        )).handle()
        hub.drain_once(t.chip)
        # Operator RESUME + RESIZE through the real admin arm, then
        # drain to empty; whatever a schedule leaves undrained is
        # completed ECANCELED and refunded by release_tenant's
        # quiesce_lane BEFORE the slot frees (conservation balances
        # without an unbounded spin, and the refund can never land on
        # a recycled slot).
        h.admin(_admin_frames(
            {"kind": P.RESUME, "tenant": "A"},
            {"kind": P.RESIZE, "tenant": "A", "core_limit": 30},
        )).handle()
        for _ in range(3):
            hub.drain_once(t.chip)
        sess._drain()
        _teardown(h, sess, t)
        # Straggler pass after release: must admit nothing.
        hub.drain_once(t.chip)

    def admin() -> None:
        # A concurrent operator racing its own SUSPEND/RESUME pair:
        # the explorer interleaves it against the client's drains and
        # the deterministic park above.
        h.admin(_admin_frames(
            {"kind": P.SUSPEND, "tenant": "A"},
            {"kind": P.RESUME, "tenant": "A"},
        )).handle()

    sched.spawn(client, "clientA")
    sched.spawn(admin, "admin")


def _setup_fastlane_multichip(h: Harness,
                              sched: mcsched.Scheduler) -> None:
    """vtpu-fastlane-everywhere: a TWO-CHIP grant's sharded lane (one
    PyRing per chip, REAL FastlaneHub drain logic — lead executes,
    follower joins the completion vector) driven through admin
    SUSPEND/RESUME/RESIZE and release while descriptors sit in both
    rings.  The fastlane-park-gate invariant judges the admit oracle
    AND — via the hub's closed-lane oracle — that every close
    transition published GATE_CLOSED on EVERY chip's ring, not just
    the lead's."""
    from ...runtime import fastlane as FL
    from ...runtime import protocol as P
    sess = h.session()

    def client() -> None:
        t = h.tenant(sess, "A", core_limit=50, devices=[0, 1])
        prog = fake_program()
        prog.out_meta = [{"shape": [16], "dtype": "float32",
                          "nbytes": 64}]
        t.executables["p"] = prog
        hub = h.state.fastlane
        rings = [FL.PyRing(8), FL.PyRing(8)]
        lane = FL.BrokerLane(t, rings, None, None, {})
        hub.lanes[t.name] = lane
        t.fastlane = lane
        rep = hub.bind_route(t, "p", [], ["o1"])
        assert rep["ok"], rep
        # One descriptor per chip ring, same seq stream (the
        # ClientLane sharded-submit shape), pre-debiting the estimate
        # on EVERY chip like rate_acquire_all.
        for _ in range(3):
            for k in range(2):
                t.chips[k].region.rate_acquire(t.slots[k], 100, 1)
            for r in rings:
                r.submit(FL.PyDesc(route=0, cost_us=100, t_sub_ns=1))
        # Park collision: drain INTO the park on both chips — the
        # gate must admit nothing on either ordinal.
        h.admin(_admin_frames(
            {"kind": P.SUSPEND, "tenant": "A"},
        )).handle()
        hub.drain_once(t.chips[0])
        hub.drain_once(t.chips[1])
        h.admin(_admin_frames(
            {"kind": P.RESUME, "tenant": "A"},
            {"kind": P.RESIZE, "tenant": "A", "core_limit": 30},
        )).handle()
        for _ in range(3):
            hub.drain_once(t.chips[0])
            hub.drain_once(t.chips[1])
        # The follower may still lag the lead's cvec by one pass.
        hub.drain_once(t.chips[1])
        sess._drain()
        _teardown(h, sess, t)
        # Straggler passes after release: must admit nothing, and the
        # closed-lane oracle must find BOTH rings gated CLOSED.
        hub.drain_once(t.chips[0])
        hub.drain_once(t.chips[1])

    def admin() -> None:
        h.admin(_admin_frames(
            {"kind": P.SUSPEND, "tenant": "A"},
            {"kind": P.RESUME, "tenant": "A"},
        )).handle()

    sched.spawn(client, "clientA")
    sched.spawn(admin, "admin")


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

SCENARIOS: List[Scenario] = [
    Scenario("batch_pipeline",
             "EXEC_BATCH pipeline with zero-RT free + journal deferral",
             _setup_batch_pipeline, with_journal=True),
    Scenario("contention",
             "two metered tenants race one chip's scheduler",
             _setup_contention, with_journal=False),
    Scenario("lease_expiry",
             "lease TTL expiry refund between executes",
             _setup_lease_expiry, with_journal=False),
    Scenario("suspend_resume",
             "admin SUSPEND/RESUME races a pipelining tenant",
             _setup_suspend_resume, with_journal=False),
    Scenario("tenant_crash",
             "connection death mid-pipeline; co-tenant unaffected",
             _setup_tenant_crash, with_journal=True),
    Scenario("multichip",
             "two-chip grant vs single-chip co-tenant",
             _setup_multichip,
             harness_kw={"n_chips": 2}, with_journal=False),
    Scenario("churn_rebind",
             "release + rebind recycles the slot mid-traffic",
             _setup_churn_rebind, with_journal=True),
    Scenario("burst_credits",
             "idle tenant banks burst credit and spends it past the "
             "frozen bucket seed",
             _setup_burst_credits,
             harness_kw={"cap_us": 12_000, "rate_lease_us": 0},
             with_journal=False),
    Scenario("burst_floor",
             "credit burster races a bucket-throttled floor-demanding "
             "co-tenant",
             _setup_burst_floor,
             harness_kw={"cap_us": 6_000, "rate_lease_us": 0,
                         "refill": True},
             with_journal=False),
    Scenario("overload_shed",
             "priority-1 batch shed at a tiny backlog cap; priority-0 "
             "admitted",
             _setup_overload_shed, with_journal=False),
    Scenario("fastlane_gate",
             "fastlane ring through SUSPEND/RESUME/RESIZE/release: no "
             "ring admit for a parked or released tenant",
             _setup_fastlane_gate, with_journal=False),
    Scenario("fastlane_multichip",
             "2-chip sharded lane (per-chip rings + completion "
             "vector) through park/RESIZE/release: no parked admit, "
             "gate closes on EVERY chip's ring",
             _setup_fastlane_multichip,
             harness_kw={"n_chips": 2}, with_journal=False),
]


def get(name: str) -> Scenario:
    for s in SCENARIOS:
        if s.name == name:
            return s
    raise KeyError(f"unknown scenario {name!r}; have "
                   f"{[s.name for s in SCENARIOS]}")
