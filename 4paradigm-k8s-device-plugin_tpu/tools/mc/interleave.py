"""vtpu-mc interleaving engine: exhaustive schedule exploration of the
real broker under the cooperative scheduler.

DFS over scheduling decisions with two classic state-space prunings:

  - **sleep sets** (DPOR-style): after exploring task ``t`` at a
    decision node, ``t`` sleeps there; an alternative ``u`` only wakes
    ``t`` in the subtree when their pending operations are DEPENDENT
    (touch the same lock/condition/queue).  Commuting interleavings of
    independent operations are explored once, not 2! times.
  - **bounded preemption** (CHESS-style): switching away from a task
    that is still enabled costs one unit of a small preemption budget;
    schedules beyond the budget are not branched.  Most concurrency
    bugs need very few preemptions, and the bound turns an intractable
    space into a dense, high-yield one.

Every schedule replays the scenario from scratch (fresh broker state,
fresh journal dir) following the recorded decision prefix, then runs
the default policy (stay on the current task; else lowest id) to a
terminal state — where the registry's terminal invariants are checked.
Replay is exact because the only nondeterminism IS the decision
sequence; a divergence is reported as a harness bug, never ignored.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import invariants as inv_registry
from . import sched as mcsched
from .harness import Harness


def _op_resource(op: Optional[Tuple]) -> Optional[int]:
    if not op or len(op) < 2 or op[1] is None:
        return None
    return id(op[1])


def _dependent(op_a: Optional[Tuple], op_b: Optional[Tuple]) -> bool:
    """Two pending operations are dependent when they touch the same
    synchronization object (lock, condition, queue).  Everything else
    commutes at the decision granularity the scheduler exposes."""
    ra, rb = _op_resource(op_a), _op_resource(op_b)
    if ra is None or rb is None:
        return True  # unknown resources: be conservative, stay sound
    return ra == rb


@dataclass
class Node:
    """One decision point along the current schedule."""
    enabled: List[int]
    ops: Dict[int, Tuple]
    chosen: int
    prev: Optional[int] = None   # task that ran the previous slice
    used_before: int = 0         # preemptions consumed before here
    tried: set = field(default_factory=set)
    sleep: set = field(default_factory=set)

    def cost(self, t: int) -> int:
        """A choice is a preemption when the previous slice's task is
        still enabled but a different one runs."""
        return 1 if (self.prev is not None and self.prev in self.enabled
                     and t != self.prev) else 0


@dataclass
class ScenarioStats:
    name: str = ""
    schedules: int = 0
    decisions: int = 0
    truncated: int = 0
    violations: List[str] = field(default_factory=list)
    # schedule (decision list) that produced the first violation
    witness: Optional[List[int]] = None


class Explorer:
    def __init__(self, scenario: "Scenario", *,
                 max_schedules: int = 2000,
                 preemption_bound: int = 2,
                 max_steps: int = mcsched.DEFAULT_MAX_STEPS) -> None:
        self.scenario = scenario
        self.max_schedules = max_schedules
        self.preemption_bound = preemption_bound
        self.max_steps = max_steps
        self.stats = ScenarioStats(name=scenario.name)

    # -- one schedule ------------------------------------------------------

    def _run_once(self, script: List[int],
                  nodes: List[Node]) -> List[str]:
        """Execute the scenario following ``script``; extend ``nodes``
        with the decision points actually taken (prefix nodes are
        reused, fresh ones appended)."""
        sched = mcsched.Scheduler(max_steps=self.max_steps)
        violations: List[str] = []
        with mcsched.patched_modules(sched):
            tmp = None
            journal = None
            if self.scenario.with_journal:
                tmp = tempfile.mkdtemp(prefix="vtpu-mc-")
                from ...runtime.journal import Journal
                journal = Journal(tmp, snapshot_every=10_000,
                                  fsync=False)
            try:
                h = Harness(sched, journal=journal,
                            **self.scenario.harness_kw)
                self.scenario.setup(h, sched)

                def choose(step: int,
                           enabled: List[mcsched.MCTask]
                           ) -> mcsched.MCTask:
                    self.stats.decisions += 1
                    by_id = {t.tid: t for t in enabled}
                    ids = sorted(by_id)
                    ops = {t.tid: t.pending for t in enabled}
                    if step < len(nodes):
                        node = nodes[step]
                        if node.chosen not in by_id:
                            raise mcsched.ReplayDivergence(
                                f"{self.scenario.name}: step {step} "
                                f"scripted task {node.chosen} not "
                                f"enabled (enabled={ids})")
                        node.enabled = ids
                        node.ops = ops
                        return by_id[node.chosen]
                    # Past the script: default policy (run-to-
                    # completion bias), recorded as a fresh node.
                    parent = nodes[-1] if nodes else None
                    prev = parent.chosen if parent else None
                    used = (parent.used_before
                            + parent.cost(parent.chosen)) \
                        if parent else 0
                    pick = prev if (prev is not None and prev in by_id) \
                        else ids[0]
                    sleep: set = set()
                    if parent is not None:
                        chosen_op = parent.ops.get(parent.chosen)
                        sleep = {
                            t for t in parent.sleep | (parent.tried
                                                       - {parent.chosen})
                            if t in ops and not _dependent(
                                ops.get(t), chosen_op)}
                    if pick in sleep:
                        awake = [i for i in ids if i not in sleep]
                        if awake:
                            pick = awake[0]
                    node = Node(enabled=ids, ops=ops, chosen=pick,
                                prev=prev, used_before=used)
                    node.tried.add(pick)
                    node.sleep = sleep
                    nodes.append(node)
                    return by_id[pick]

                sched.run(choose)
                violations.extend(sched.violations)
                if not violations and sched.steps <= self.max_steps:
                    violations.extend(inv_registry.run_checks(
                        "interleave", "terminal", h))
                if sched.steps > self.max_steps:
                    self.stats.truncated += 1
            finally:
                if journal is not None:
                    journal.close()
                if tmp is not None:
                    shutil.rmtree(tmp, ignore_errors=True)
        return violations

    # -- DFS over schedules ------------------------------------------------

    def explore(self) -> ScenarioStats:
        nodes: List[Node] = []
        script: List[int] = []
        while True:
            try:
                violations = self._run_once(script, nodes)
            except mcsched.ReplayDivergence as e:
                self.stats.violations.append(f"[determinism] {e}")
                self.stats.witness = list(script)
                break
            self.stats.schedules += 1
            if violations:
                self.stats.violations.extend(violations)
                self.stats.witness = [n.chosen for n in nodes]
                break
            if self.stats.schedules >= self.max_schedules:
                break
            # Backtrack: deepest node with an unexplored, awake,
            # budget-feasible alternative.
            nxt = None
            while nodes:
                node = nodes[-1]
                feasible = [
                    t for t in node.enabled
                    if t not in node.tried and t not in node.sleep
                    and node.used_before + node.cost(t)
                    <= self.preemption_bound]
                if feasible:
                    t = feasible[0]
                    node.tried.add(t)
                    new = Node(enabled=node.enabled, ops=node.ops,
                               chosen=t, prev=node.prev,
                               used_before=node.used_before)
                    new.tried = node.tried  # shared explored set
                    new.sleep = set(node.sleep)
                    nodes[-1] = new
                    nxt = [n.chosen for n in nodes]
                    break
                nodes.pop()
            if nxt is None:
                break  # space exhausted
            script = nxt
            nodes = nodes[:len(script)]
            for n in nodes:
                n.ops = dict(n.ops)
        return self.stats


@dataclass
class Scenario:
    name: str
    description: str
    setup: Callable[[Harness, mcsched.Scheduler], None]
    harness_kw: Dict[str, Any] = field(default_factory=dict)
    with_journal: bool = True


def explore_scenario(scenario: Scenario, **kw: Any) -> ScenarioStats:
    return Explorer(scenario, **kw).explore()
