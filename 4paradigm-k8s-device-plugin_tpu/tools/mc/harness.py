"""vtpu-mc broker-under-test harness.

Builds the REAL broker objects — ``RuntimeState``, ``Tenant``,
``DeviceScheduler``, ``TenantSession``, ``Journal`` — on top of the
cooperative scheduler's shims (sched.py), with exactly two stand-ins:

  - **ModelRegion** replaces the native mmap'd accounting region.  The
    native region is lock-free C (its own TSan job proves it); what the
    model checker explores is the PYTHON broker logic around it, so the
    model keeps the same API and — crucially — double-entry counters
    (net bucket debit, busy billed, ledger bounds) that the invariant
    registry checks against the broker's own state.
  - **FakeJax / fake programs** replace device execution: a dispatch
    "runs" by returning fake output arrays with static shapes, which is
    all the broker's accounting paths ever look at.

Everything else — scheduling, lease grant/burn/refund, queue/retire
bookkeeping, journal deferral and replay — is the genuine code from
``runtime/server.py`` / ``runtime/journal.py``.  The stubs are built
with ``__new__`` + explicit field seeding (mirroring
``RuntimeState.__init__`` minus the jax/chip-claim machinery) so no
socket, no device and no wall clock is ever involved.
"""

from __future__ import annotations

import collections
from typing import Any, Dict, List, Optional, Tuple

from . import sched as mcsched

MAX_SLOTS = 16


class RegionStats:
    __slots__ = ("used_bytes", "limit_bytes", "peak_bytes",
                 "core_limit_pct", "n_procs")

    def __init__(self, used: int, limit: int, peak: int, core: int,
                 n_procs: int = 0) -> None:
        self.used_bytes = used
        self.limit_bytes = limit
        self.peak_bytes = peak
        self.core_limit_pct = core
        self.n_procs = n_procs


class ModelRegion:
    """Deterministic in-process model of the native shared region's
    accounting semantics, instrumented for conservation checking.

    ``refill=False`` (the conservation configuration) freezes the token
    bucket at its seed level: every debit/credit is then exactly
    auditable — ``net_debit`` must equal metered busy time plus
    outstanding leases at any quiescent point, and the level may never
    exceed the seed (a refund that does is a double credit).
    ``refill=True`` models the real work-accruing bucket for
    throttling scenarios (credits clamp at capacity, like the native
    bucket)."""

    def __init__(self, clock: mcsched.MCClock, nslots: int = MAX_SLOTS,
                 cap_us: float = 10**9, refill: bool = False) -> None:
        self.clock = clock
        self.nslots = nslots
        self.cap_us = float(cap_us)
        self.refill = refill
        self.limit = [0] * nslots
        self.used = [0] * nslots
        self.peak = [0] * nslots
        self.core = [0] * nslots
        self.level = [float(cap_us)] * nslots
        self.busy = [0] * nslots
        self.busy_base = [0] * nslots
        self.net_debit = [0.0] * nslots
        self.last_refill = [clock.now()] * nslots
        self.violations: List[str] = []

    # -- token bucket ------------------------------------------------------

    def _tick(self, d: int) -> None:
        now = self.clock.now()
        if self.refill and self.core[d] > 0:
            dt = max(now - self.last_refill[d], 0.0)
            rate = self.core[d] / 100.0 * 1e6  # us of budget per s
            self.level[d] = min(self.level[d] + dt * rate, self.cap_us)
        self.last_refill[d] = now

    def rate_acquire(self, d: int, cost_us: int,
                     priority: int = 1) -> int:
        self._tick(d)
        if priority == 0 or self.level[d] >= cost_us:
            self.level[d] -= cost_us
            self.net_debit[d] += cost_us
            return 0
        short = cost_us - self.level[d]
        rate = max(self.core[d], 1) / 100.0 * 1e6
        return int(short / rate * 1e9) + 1  # ns until refilled enough

    def rate_adjust(self, d: int, delta_us: int) -> None:
        self._tick(d)
        self.level[d] -= delta_us
        self.net_debit[d] += delta_us
        if not self.refill and self.level[d] > self.cap_us + 1e-6:
            self.violations.append(
                f"bucket over-credited on slot {d}: level "
                f"{self.level[d]:.0f}us exceeds seed {self.cap_us:.0f}us "
                f"(double refund)")
        if self.refill:
            self.level[d] = min(self.level[d], self.cap_us)

    def rate_level(self, d: int) -> int:
        self._tick(d)
        return int(self.level[d])

    def busy_add(self, d: int, us: int) -> None:
        self.busy[d] += int(us)

    # -- HBM ledger --------------------------------------------------------

    def mem_acquire(self, d: int, nbytes: int,
                    oversubscribe: bool = False) -> bool:
        if not oversubscribe and self.limit[d] and \
                self.used[d] + nbytes > self.limit[d]:
            return False
        self.used[d] += nbytes
        self.peak[d] = max(self.peak[d], self.used[d])
        return True

    def mem_acquire_capped(self, d: int, nbytes: int,
                           cap_bytes: int) -> bool:
        if self.used[d] + nbytes > cap_bytes:
            return False
        self.used[d] += nbytes
        self.peak[d] = max(self.peak[d], self.used[d])
        return True

    def mem_release(self, d: int, nbytes: int) -> None:
        self.used[d] -= nbytes
        if self.used[d] < 0:
            self.violations.append(
                f"HBM ledger negative on slot {d}: {self.used[d]} "
                f"after releasing {nbytes} (double release)")

    def mem_info(self, d: int) -> Tuple[int, int]:
        free = max(self.limit[d] - self.used[d], 0) \
            if self.limit[d] else 0
        return free, self.limit[d]

    # -- slot admin --------------------------------------------------------

    def device_stats(self, d: int) -> RegionStats:
        return RegionStats(self.used[d], self.limit[d], self.peak[d],
                           self.core[d])

    def set_mem_limit(self, d: int, limit_bytes: int) -> None:
        self.limit[d] = int(limit_bytes)

    def set_core_limit(self, d: int, pct: int) -> None:
        self.core[d] = int(pct)

    def reset_slot(self, d: int) -> None:
        # Slot recycle: bucket re-seeds; busy is a monotonic counter
        # the real region keeps — conservation rebases on it.
        self.level[d] = self.cap_us
        self.net_debit[d] = 0.0
        self.busy_base[d] = self.busy[d]
        self.last_refill[d] = self.clock.now()

    def busy_since_reset(self, d: int) -> int:
        return self.busy[d] - self.busy_base[d]

    def set_work_conserving(self, on: bool) -> None:
        pass

    def register(self, host_pid: int = 0) -> int:
        return 0

    def close(self) -> None:
        pass


class FakeDevice:
    def __init__(self, index: int) -> None:
        self.id = index
        self.platform = "mc"
        self.coords = (index,)


class FakeArray:
    """Static-shape output array: everything the broker's accounting
    reads off a dispatched program's result."""

    def __init__(self, nbytes: int = 64, shape: Tuple[int, ...] = (16,),
                 dtype: str = "float32") -> None:
        self.nbytes = nbytes
        self.shape = shape
        self.dtype = dtype

    def block_until_ready(self) -> "FakeArray":
        return self


class _FakeJit:
    """jit(fn) stand-in with the .lower(...).compile() AOT surface the
    COMPILE arm drives."""

    def __init__(self, fn: Any) -> None:
        self.fn = fn

    def lower(self, *avals: Any) -> "_FakeJit":
        return self

    def compile(self) -> "_FakeJit":
        return self

    def __call__(self, *args: Any) -> Any:
        return self.fn(*args)


class _FakeExported:
    """jax.export.Exported stand-in, decoded from an mc program blob
    (``fake_blob``): carries exactly the attrs ``cached_blob`` reads."""

    def __init__(self, n_outs: int, out_nbytes: int) -> None:
        self.in_avals = ()
        self.out_avals = [None] * n_outs
        self.nr_devices = 1
        self._n_outs = n_outs
        self._out_nbytes = out_nbytes

    def call(self, *args: Any) -> List[FakeArray]:
        return [FakeArray(nbytes=self._out_nbytes)
                for _ in range(self._n_outs)]


class _FakeExportNS:
    @staticmethod
    def deserialize(blob: Any) -> _FakeExported:
        parts = bytes(blob).decode("ascii", "replace").split(":")
        if len(parts) != 3 or parts[0] != "mc-prog":
            raise ValueError(f"not an mc program blob: {parts[:1]}")
        return _FakeExported(int(parts[1]), int(parts[2]))


def fake_blob(n_outs: int = 1, out_nbytes: int = 64) -> bytes:
    """A serialized-export stand-in the harness FakeJax can
    'deserialize' — lets scenarios drive the REAL COMPILE arm
    (``cached_blob`` + journal blob store) without real jax."""
    return b"mc-prog:%d:%d" % (n_outs, out_nbytes)


class FakeJax:
    """The jax surface the dispatch/metering/compile paths touch."""

    export = _FakeExportNS()

    def block_until_ready(self, x: Any) -> Any:
        return x

    def device_put(self, arr: Any, dev: Any) -> FakeArray:
        nb = int(getattr(arr, "nbytes", 64))
        return FakeArray(nbytes=nb)

    def jit(self, fn: Any, **kw: Any) -> _FakeJit:
        return _FakeJit(fn)

    @staticmethod
    def ShapeDtypeStruct(shape: Any, dtype: Any) -> Tuple[Any, Any]:
        return (shape, dtype)


class ScriptSock:
    """Scripted in-memory socket: the pre-encoded request frames of one
    connection, replayed through the REAL protocol layer
    (``P.recv_msg``) into the REAL ``TenantSession._serve`` /
    ``AdminSession.handle`` loops.  recv() past the script returns
    b'' — the peer-closed signal that drives the genuine teardown
    path.  Replies land in ``sent`` (bytes) for inspection."""

    def __init__(self, frames: Any = ()) -> None:
        self._buf = b"".join(frames)
        self._off = 0
        self.sent: List[bytes] = []

    def recv(self, n: int) -> bytes:
        out = self._buf[self._off:self._off + n]
        self._off += len(out)
        return out

    def sendall(self, data: Any) -> None:
        self.sent.append(bytes(data))

    def getsockopt(self, level: int, opt: int, buflen: int = 0) -> bytes:
        import os
        import struct
        return struct.pack("3i", os.getpid(), os.getuid(), os.getgid())


def fake_program(n_outs: int = 1, out_nbytes: int = 64):
    """A real ``Program`` whose callable returns fake static-shape
    outputs (what the metering/accounting paths consume)."""
    from ...runtime.server import Program

    def fn(*args: Any) -> List[FakeArray]:
        return [FakeArray(nbytes=out_nbytes) for _ in range(n_outs)]

    return Program(fn, avals=(), n_outs=n_outs)


class FakeChip:
    """ChipState stand-in: model region + the REAL DeviceScheduler
    (whose dispatcher/completer threads become MC daemon tasks via the
    patched ``threading.Thread``)."""

    def __init__(self, state: Any, index: int, clock: mcsched.MCClock,
                 cap_us: float, refill: bool) -> None:
        self.index = index
        self.device = FakeDevice(index)
        self.region = ModelRegion(clock, cap_us=cap_us, refill=refill)
        self._latency_us = 0.0
        from ...runtime.server import DeviceScheduler
        self.scheduler = DeviceScheduler(state, self)

    def calibrate_latency_us(self) -> float:
        return 0.0


class Harness:
    """One scenario's broker instance + the oracles the invariant
    registry reads."""

    def __init__(self, sched: mcsched.Scheduler, *,
                 n_chips: int = 1, journal: Any = None,
                 rate_lease_us: int = 20_000, cap_us: float = 10**9,
                 refill: bool = False, min_exec_cost_us: int = 0,
                 default_hbm: int = 1 << 20,
                 default_core: int = 50) -> None:
        self.sched = sched
        self.clock = sched.clock
        self.refill = refill
        self.sent: List[Tuple[str, Dict[str, Any]]] = []
        self.lost_wakes: List[str] = []
        self.durability: List[str] = []
        self._dur_seen: Dict[str, set] = {}
        # Every tenant the scenario ever bound (incl. released ones):
        # the terminal deferred-flush invariant scans them all.
        self.all_tenants: List[Any] = []
        self.state = self._build_state(
            n_chips, journal, rate_lease_us, cap_us, refill,
            min_exec_cost_us, default_hbm, default_core)
        sched.on_timeout_wake = self._on_timeout_wake
        sched.quiescent = self.quiescent
        sched.step_check = self._step_check

    # -- construction ------------------------------------------------------

    def _build_state(self, n_chips: int, journal: Any,
                     rate_lease_us: int, cap_us: float, refill: bool,
                     min_exec_cost_us: int, default_hbm: int,
                     default_core: int) -> Any:
        from ...runtime import server as S
        from ...runtime import trace as tracing
        st = S.RuntimeState.__new__(S.RuntimeState)
        st.jax = FakeJax()
        st.journal = journal
        st.prev_epoch = None
        st.recovered = {}
        st.resume_grace = 120.0
        st.recovery = {k: 0 for k in (
            "recoveries_total", "tenants_recovered", "tenants_readopted",
            "tenants_dropped_dead", "tenants_dropped_expired",
            "tenants_dropped_replaced", "arrays_dropped",
            "corrupt_recoveries")}
        st.chip_latency_hints = {}
        st.draining = False
        st._keeper_stop = mcsched.MCEvent(self.sched)
        st.flight = tracing.FlightRecorder(enabled=False)
        st.last_wedge = None
        # SLO plane disabled under MC: its internal clock reads are
        # wall-time (not the model clock), and the invariants under
        # test are quota/lease/crash ones — the plane's own properties
        # have their own suite (tests/test_slo.py).
        from ...runtime import slo as slo_mod
        st.slo = slo_mod.SloPlane(enabled=False)
        st._journal_state = None
        st.work_conserving = False
        st.spill_overshoot = 0.0
        st.rate_lease_us = rate_lease_us
        st.rate_lease_ttl_s = max(4.0 * rate_lease_us / 1e6, 0.05)
        st.pool_stats = {}
        st.devices = [FakeDevice(i) for i in range(n_chips)]
        st.epoch = "mc-epoch"
        st.region_path = "<mc>"
        st.default_hbm = default_hbm
        st.default_core = default_core
        st.min_exec_cost_us = min_exec_cost_us
        st.tenants = {}
        # vtpu-elastic admission control, with the mc shed oracle armed
        # (the broker records every shed decision into it; the
        # shed-precedence invariant judges the log).
        st.admission = S.AdmissionState()
        st.admission.shed_log = []
        # vtpu-fastlane hub in MANUAL mode (no drainer threads — the
        # fastlane scenario drives drain_once cooperatively over a
        # PyRing) with the admission oracle armed for the ring
        # park-gate invariant.
        # vtpu-timers: NO wheel under mc — the schedulers take their
        # legacy bounded idle timeouts, which the cooperative clock
        # model understands (a wheel thread would add an opaque
        # wall-clock actor to every schedule).
        st.timers = None
        st.fastlane = S.fastlane_mod.FastlaneHub(st)
        st.fastlane.manual = True
        st.fastlane.admit_log = []
        # vtpu-failover replication hub (docs/FAILOVER.md): inert with
        # no follower; the STATS arms read its status block, and the
        # crash engine's canned session drives the real MIGRATE arm.
        st.replication = S.repl_mod.ReplicationHub(st)
        st.suspended = set()
        st.blob_cache = collections.OrderedDict()
        st.chain_cache = collections.OrderedDict()
        st.put_cache = {}
        st.put_dedup = False
        st.put_dedup_node = False
        # Locks via the patched server-module namespace, exactly as
        # RuntimeState.__init__ would create them.
        st.put_cache_mu = S.threading.Lock()
        st.mu = S.threading.Lock()
        st.chips_mu = S.threading.Lock()
        st.chips = {}
        for i in range(n_chips):
            st.chips[i] = FakeChip(st, i, self.clock, cap_us, refill)
            # Arm the credit oracle: every burst-credit mint / spend /
            # floor-guard denial is recorded for the credit invariants.
            st.chips[i].scheduler.credit_log = []
        return st

    def session(self, sock: Optional[ScriptSock] = None) -> Any:
        """A real TenantSession wired to the stub state with the socket
        send replaced by a recorder (+ the reply-durability oracle).
        With ``sock`` set, ``sess.request`` is wired so a scenario task
        can run the REAL ``handle()`` loop over scripted frames."""
        from ...runtime import protocol as P
        from ...runtime import server as S
        sess = S.TenantSession.__new__(S.TenantSession)
        sess.state = self.state
        if sock is not None:
            sess.request = sock
        sess.send_mu = S.threading.Lock()
        sess.pending = 0
        sess.pending_cond = S.threading.Condition()
        sess._staging = {}
        sess._staging_bytes = 0
        sess._pool = P.RecvPool(stats=self.state.pool_stats)

        def _send(msg: Dict[str, Any], _sess=sess) -> None:
            # Durability contract: once the client sees a reply, the
            # journal covers the change — every pre-reply path flushes
            # the tenant's deferred records first.  A record may
            # legitimately be in flight for ONE concurrent reply (a
            # co-task deferred it after this reply's flush); one that
            # is still deferred at the tenant's NEXT reply was never
            # flushed at all (the lost-durability bug).
            t = getattr(_sess, "_mc_tenant", None)
            if self.state.journal is not None and t is not None:
                pending = {id(r) for r in t.pending_journal}
                stale = pending & self._dur_seen.get(t.name, set())
                if stale:
                    self.durability.append(
                        f"reply sent while tenant {t.name!r} still "
                        f"holds {len(stale)} deferred journal "
                        f"record(s) from before its previous reply "
                        f"(deferred append never flushed)")
                self._dur_seen[t.name] = pending
            self.sent.append(("send", msg))

        sess._send = _send
        return sess

    def tenant(self, sess: Any, name: str, priority: int = 1,
               core_limit: int = 50, hbm_limit: Optional[int] = None,
               device: int = 0,
               devices: Optional[List[int]] = None) -> Any:
        t, _created = self.state.tenant(
            name, priority, device=device, devices=devices,
            hbm_limit=hbm_limit if hbm_limit is not None
            else self.state.default_hbm,
            core_limit=core_limit)
        if self.state.journal is not None:
            import os
            sess._journal_bind(t, {"pid": os.getpid(), "pidns": 0})
        sess._mc_tenant = t
        if t not in self.all_tenants:
            self.all_tenants.append(t)
        return t

    def admin(self, frames: Any) -> Any:
        """A real AdminSession over a scripted socket: a scenario task
        calls ``.handle()`` to drive the genuine admin verbs
        (SUSPEND/RESUME/DRAIN/...) against the stub state."""
        from ...runtime import server as S
        adm = S.AdminSession.__new__(S.AdminSession)
        adm.state = self.state
        adm.request = ScriptSock(frames)
        return adm

    def seed_array(self, t: Any, aid: str, nbytes: int = 64) -> None:
        """Stage an input array through the real charge path (and, with
        a journal, the real PUT bookkeeping order: blob_meta under
        t.mu, the put record appended after release — so a later drop
        of this id defers a del record exactly like a journaled PUT
        array's would)."""
        rec = {"op": "put", "name": t.name, "id": aid,
               "sha": f"mc-{aid}", "shape": [nbytes // 4],
               "dtype": "float32", "nbytes": nbytes,
               "charges": [[0, nbytes]], "spilled": False}
        with t.mu:
            t.arrays[aid] = FakeArray(nbytes=nbytes)
            t.nbytes[aid] = nbytes
            t.charge_array(aid, [(0, nbytes)], False)
            if self.state.journal is not None:
                t.blob_meta[aid] = {
                    k: rec[k] for k in ("sha", "shape", "dtype",
                                        "nbytes", "charges", "spilled")}
        if self.state.journal is not None:
            self.state.journal.append(rec)

    def exec_spec(self, exe: str, args: List[str], outs: List[str],
                  free: Tuple[str, ...] = ()) -> Dict[str, Any]:
        return {"exe": exe, "args": args, "outs": outs,
                "free": list(free)}

    # -- oracles -----------------------------------------------------------

    def _on_timeout_wake(self, task: mcsched.MCTask, obj: Any,
                         timeout: float) -> None:
        """Lost-wake oracle: the dispatcher idle-slept (its 0.5 s
        default — used only when _pick_locked reported no time-gated
        work) yet its scheduler holds dispatchable work.  A correct
        broker's submit/retire/resume paths would have notified it."""
        if not task.name.startswith("vtpu-rt-dispatch"):
            return
        if timeout < 0.49:  # soonest-bounded waits are time-gated work
            return
        from ...runtime import server as S
        for chip in self.state.chips.values():
            ds = chip.scheduler
            if not isinstance(obj, mcsched.MCCondition) or \
                    obj is not ds.mu:
                continue
            if ds.queued_est_us >= S.MAX_QUEUED_US:
                continue
            now = self.clock.now()
            for name, q in ds.queues.items():
                if not q or name in self.state.suspended \
                        or name in ds.preempted:
                    continue
                if ds.inflight.get(name, 0) >= S.MAX_INFLIGHT:
                    continue
                if ds.not_ready_until.get(name, 0.0) > now:
                    continue
                self.lost_wakes.append(
                    f"dispatcher chip{chip.index} idle-slept with "
                    f"dispatchable work queued for tenant {name!r} "
                    f"(lost wake)")

    def quiescent(self) -> bool:
        for chip in self.state.chips.values():
            ds = chip.scheduler
            if any(ds.inflight.values()):
                return False
            if ds._completion_q.items:  # MCQueue
                return False
            for name, q in ds.queues.items():
                if q and name not in self.state.suspended \
                        and name not in ds.preempted:
                    return False
        return True

    def _step_check(self) -> List[str]:
        from . import invariants
        return invariants.run_checks("interleave", "step", self)

    def expected_hbm(self) -> Dict[Tuple[int, int], int]:
        """chip,slot -> bytes the broker's OWN books say are charged
        (tenant charges + resident staged spill copies)."""
        out: Dict[Tuple[int, int], int] = {}
        live = list(self.state.tenants.values()) \
            + [e[0] for e in self.state.recovered.values()]
        for t in live:
            for charges in t.charges.values():
                for pos, nb in charges:
                    key = (t.chips[pos].index, t.slots[pos])
                    out[key] = out.get(key, 0) + nb
            for nb in t.staged_bytes.values():
                key = (t.chip.index, t.index)
                out[key] = out.get(key, 0) + nb
        return out
