"""Seeded-violation selfcheck: prove every invariant's checker still
catches the bug class it exists for.

A model checker that reports "0 violations" is only trustworthy if a
DELIBERATELY broken broker makes it scream.  Each seed below patches
one real broker/journal code path into a known-bad variant (a refund
that doesn't refund, a notify that doesn't notify, a replay arm that
skips records, ...), runs the matching engine, and requires the named
invariant to fire.  ``python -m vtpu.tools.mc --selfcheck`` runs the
whole matrix (CI does); tests/test_mc.py drives the same seeds
individually.

The patches live HERE, never in the broker: broker source stays
correct, and a seed that stops firing means the CHECKER regressed.
"""

from __future__ import annotations

import contextlib
import os
import shutil
import tempfile
from dataclasses import dataclass
from typing import Any, Callable, Iterator, List, Optional, Tuple

from . import clustercut, crashcut, interleave, scenarios


@dataclass(frozen=True)
class Seed:
    name: str
    engine: str            # "interleave" | "crash"
    invariant: str         # registry invariant expected to fire
    scenario: str          # interleave scenario (ignored for crash)
    patch: Callable[[], Any]  # contextmanager applying the broken code


# ---------------------------------------------------------------------------
# Interleave-engine seeds
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def _seed_broken_refund() -> Iterator[None]:
    """lease_release forgets the bucket refund: quota leaks on
    expiry/suspend/teardown."""
    from ...runtime import server as S
    orig = S.Tenant.lease_release

    def broken(self: Any) -> None:
        self.lease_us = 0.0
        self.lease_exp = 0.0   # the refund never happens

    S.Tenant.lease_release = broken
    try:
        yield
    finally:
        S.Tenant.lease_release = orig


@contextlib.contextmanager
def _seed_dropped_wake() -> Iterator[None]:
    """submit/retire notify is dropped: the dispatcher only ever wakes
    by timeout."""
    from ...runtime import server as S
    orig = S.DeviceScheduler._notify_locked
    S.DeviceScheduler._notify_locked = lambda self: None
    try:
        yield
    finally:
        S.DeviceScheduler._notify_locked = orig


@contextlib.contextmanager
def _seed_double_release() -> Iterator[None]:
    """release_array releases the ledger twice (the double-free class
    the region's negative-ledger guard exists for)."""
    from ...runtime import server as S
    orig = S.Tenant.release_array

    def double(self: Any, aid: str, default_nbytes: int = 0) -> None:
        charges = self.charges.get(aid)
        orig(self, aid, default_nbytes)
        if charges:
            for pos, nb in charges:
                self.chips[pos].region.mem_release(self.slots[pos], nb)

    S.Tenant.release_array = double
    try:
        yield
    finally:
        S.Tenant.release_array = orig


@contextlib.contextmanager
def _seed_cleanup_leak() -> Iterator[None]:
    """Teardown skips the array drops: HBM stays charged after the
    tenant is gone."""
    from ...runtime import server as S
    orig = S.TenantSession._cleanup
    S.TenantSession._cleanup = lambda self, t: None
    try:
        yield
    finally:
        S.TenantSession._cleanup = orig


@contextlib.contextmanager
def _seed_lease_overburn() -> Iterator[None]:
    """Lease admission burns without checking the balance: the
    pre-debited budget goes negative (unmetered device time)."""
    from ...runtime import server as S
    orig = S.DeviceScheduler._lease_admit_locked

    def overburn(self: Any, t: Any, est: float, now: float) -> int:
        q = float(self.state.rate_lease_us)
        if q <= 0:
            return orig(self, t, est, now)
        if t.lease_us <= 0.0:
            return orig(self, t, est, now)
        t.lease_us -= 5.0 * est   # burns 5x the grant, never re-syncs
        return 0

    S.DeviceScheduler._lease_admit_locked = overburn
    try:
        yield
    finally:
        S.DeviceScheduler._lease_admit_locked = orig


@contextlib.contextmanager
def _seed_unflushed_journal() -> Iterator[None]:
    """Deferred journal records are never flushed: a reply acknowledges
    state the journal does not yet carry."""
    from ...runtime import server as S
    orig = S.flush_tenant_journal
    S.flush_tenant_journal = lambda state, t: None
    try:
        yield
    finally:
        S.flush_tenant_journal = orig


@contextlib.contextmanager
def _seed_credit_mint_nothing() -> Iterator[None]:
    """Accrual mints a fat constant per call instead of pricing the
    idle window at the core share: credit appears from nothing."""
    from ...runtime import server as S
    orig = S.DeviceScheduler._mint_credit_locked

    def fabricate(self: Any, t: Any, now: float) -> None:
        t.credit_us += 1_000_000.0
        t.credit_minted_us += 1_000_000.0

    S.DeviceScheduler._mint_credit_locked = fabricate
    try:
        yield
    finally:
        S.DeviceScheduler._mint_credit_locked = orig


@contextlib.contextmanager
def _seed_floor_violated() -> Iterator[None]:
    """The credit-spend path ignores the floor guard: a burster keeps
    spending while a co-tenant with backlog sits bucket-throttled (the
    contention snapshot is still computed and logged truthfully — only
    the DENY decision is dropped)."""
    from ...runtime import server as S

    def no_guard(self: Any, t: Any, est: float, now: float) -> bool:
        if S.BURST_CAP_US <= 0 or t.credit_us < est:
            return False
        contended = tuple(
            n for n, q in self.queues.items()
            if q and n != t.name and n not in self.preempted
            and self.not_ready_until.get(n, 0.0) > now)
        t.credit_us -= est
        t.credit_spent_us += est
        t.last_admit_credit = True
        if self.credit_log is not None:
            self.credit_log.append(("spend", t.name, est, contended))
        return True

    orig = S.DeviceScheduler._credit_admit_locked
    S.DeviceScheduler._credit_admit_locked = no_guard
    try:
        yield
    finally:
        S.DeviceScheduler._credit_admit_locked = orig


@contextlib.contextmanager
def _seed_shed_floor_demander() -> Iterator[None]:
    """Shedding inverted: the floor-demanding priority-0 class sheds
    FIRST (at a 0.1 backlog fraction) while the lower priorities hold
    out to the cap."""
    from ...runtime import server as S
    orig = S.AdmissionState.shed_fraction

    def inverted(self: Any, priority: int) -> float:
        return 0.1 if priority <= 0 else 1.0

    S.AdmissionState.shed_fraction = inverted
    try:
        yield
    finally:
        S.AdmissionState.shed_fraction = orig


# ---------------------------------------------------------------------------
# Crash-engine seeds
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def _seed_skipped_replay_arm() -> Iterator[None]:
    """_apply_record loses its 'del' arm: recovery resurrects deleted
    arrays (and their ledger bytes)."""
    from ...runtime import journal as J
    orig = J._apply_record

    def skip_del(state: Any, rec: Any) -> None:
        if rec.get("op") == "del":
            return
        orig(state, rec)

    J._apply_record = skip_del
    try:
        yield
    finally:
        J._apply_record = orig


@contextlib.contextmanager
def _seed_nondeterministic_replay() -> Iterator[None]:
    """Replay applies EMA records only on every second recovery: two
    recoveries of one prefix disagree."""
    from ...runtime import journal as J
    orig_apply = J._apply_record
    orig_load = J.Journal.load_state
    flip = {"n": 0}

    def flaky_apply(state: Any, rec: Any) -> None:
        if rec.get("op") == "ema" and flip["n"] % 2 == 1:
            return
        orig_apply(state, rec)

    def counting_load(self: Any) -> Any:
        out = orig_load(self)
        flip["n"] += 1
        return out

    J._apply_record = flaky_apply
    J.Journal.load_state = counting_load
    try:
        yield
    finally:
        J._apply_record = orig_apply
        J.Journal.load_state = orig_load


@contextlib.contextmanager
def _seed_grant_not_reseeded() -> Iterator[None]:
    """Recovery forgets to re-seed the region limits from the journaled
    grant: quotas silently revert to broker defaults."""
    from . import harness as H
    orig = H.ModelRegion.set_mem_limit
    H.ModelRegion.set_mem_limit = lambda self, d, limit_bytes: None
    try:
        yield
    finally:
        H.ModelRegion.set_mem_limit = orig


@contextlib.contextmanager
def _seed_lossy_snapshot() -> Iterator[None]:
    """The boot snapshot drops a tenant: the SECOND crash after a
    recovery loses state the first recovery still had."""
    from ...runtime import server as S
    orig = S.RuntimeState._snapshot_dict

    def lossy(self: Any) -> dict:
        out = orig(self)
        if out.get("tenants"):
            out["tenants"].pop(sorted(out["tenants"])[0])
        return out

    S.RuntimeState._snapshot_dict = lossy
    try:
        yield
    finally:
        S.RuntimeState._snapshot_dict = orig


@contextlib.contextmanager
def _seed_overdropped_tail() -> Iterator[None]:
    """Tail handling drops one record too many: a torn-tail recovery
    loses a COMMITTED record."""
    from ...runtime import journal as J
    orig = J.Journal._parse_lines

    def overdrop(data: bytes, tail_tolerant: bool) -> list:
        out = orig(data, tail_tolerant)
        if tail_tolerant and out:
            out = out[:-1]
        return out

    J.Journal._parse_lines = staticmethod(overdrop)
    try:
        yield
    finally:
        J.Journal._parse_lines = staticmethod(orig)


@contextlib.contextmanager
def _seed_corruption_swallowed() -> Iterator[None]:
    """Mid-log damage is silently skipped instead of failing closed:
    recovery proceeds on a log it cannot trust."""
    from ...runtime import journal as J
    orig = J.Journal._parse_lines

    def swallow(data: bytes, tail_tolerant: bool) -> list:
        try:
            return orig(data, tail_tolerant)
        except J.JournalCorrupt:
            # "Best effort": parse what still frames — the exact
            # guessed-quota-state behavior the contract bans.
            out = []
            for line in data.split(b"\n"):
                try:
                    recs = orig(line + b"\n", True)
                except (J.JournalCorrupt, ValueError):
                    continue
                out.extend(recs)
            return out

    J.Journal._parse_lines = staticmethod(swallow)
    try:
        yield
    finally:
        J.Journal._parse_lines = staticmethod(orig)


@contextlib.contextmanager
def _seed_lossy_migration() -> Iterator[None]:
    """The migrate replay arm silently drops one of the tenant's
    arrays: the recovered charge books fall short of the independent
    reading — migration stopped conserving the ledger."""
    from ...runtime import journal as J
    orig = J._apply_record

    def lossy(state: Any, rec: Any) -> None:
        orig(state, rec)
        if rec.get("op") == "migrate":
            t = state.get("tenants", {}).get(rec.get("name"))
            if t and t.get("arrays"):
                t["arrays"].pop(sorted(t["arrays"])[0])

    J._apply_record = lossy
    try:
        yield
    finally:
        J._apply_record = orig


@contextlib.contextmanager
def _seed_diverging_stream_apply() -> Iterator[None]:
    """The standby's stream applier silently skips EMA records: its
    applied state diverges from the independent reading — the bounded
    lag is a lie (the takeover would serve stale cost models)."""
    from ...runtime import journal as J
    from ...runtime import replication as R
    orig = R.apply_stream

    def skipping(state: Any, data: bytes, leftover: bytes = b""):
        recs, _complete, rest = R.split_complete(leftover + data)
        for rec in recs:
            if rec.get("op") == "ema":
                continue
            J._apply_record(state, rec)
        return len(recs), rest

    R.apply_stream = skipping
    try:
        yield
    finally:
        R.apply_stream = orig


@contextlib.contextmanager
def _seed_torn_stream_applied() -> Iterator[None]:
    """The stream framing swallows CRC damage 'best effort' (parse
    whatever still frames, skip the rest): a corrupted chunk mutates
    standby state instead of forcing the snapshot re-bootstrap."""
    from ...runtime import journal as J
    from ...runtime import replication as R
    orig = R.split_complete

    def swallow(data: bytes):
        try:
            return orig(data)
        except R.StreamCorrupt:
            out = []
            for line in data.split(b"\n"):
                try:
                    out.extend(J.Journal._parse_lines(line + b"\n",
                                                      False))
                except (J.JournalCorrupt, ValueError):
                    continue
            return out, data, b""

    R.split_complete = swallow
    try:
        yield
    finally:
        R.split_complete = orig


@contextlib.contextmanager
def _seed_unfenced_stale_primary() -> Iterator[None]:
    """The fence check is blinded: a stale primary whose epoch a
    takeover superseded keeps passing — it could still journal, and
    therefore still ack (the exact split-brain the fence exists to
    ban)."""
    from ...runtime import replication as R
    orig = R.Fence.check
    R.Fence.check = lambda self: None
    try:
        yield
    finally:
        R.Fence.check = orig


@contextlib.contextmanager
def _seed_fastlane_park_ignored() -> Iterator[None]:
    """The fastlane drainer's park verdict is blinded: a suspended/
    preempted tenant's ring keeps executing.  The admit oracle reads
    ground truth independently, so the fastlane-park-gate row must
    fire."""
    from ...runtime import fastlane as FL
    # Capture the staticmethod DESCRIPTOR (class __dict__), not the
    # bound function: restoring a plain function would turn the
    # attribute into an instance method and shift every later call by
    # one argument.
    orig_desc = FL.FastlaneHub.__dict__["_park_verdict"]
    orig_fn = orig_desc.__func__

    @staticmethod
    def blind(state: Any, sched: Any, t: Any, now: float):
        _parked, probation, contended = orig_fn(state, sched, t, now)
        return False, probation, contended  # the park never bites

    FL.FastlaneHub._park_verdict = blind
    try:
        yield
    finally:
        FL.FastlaneHub._park_verdict = orig_desc


@contextlib.contextmanager
def _seed_gate_close_lead_only() -> Iterator[None]:
    """A sharded lane's close transition gates only the LEAD ring:
    the follower ordinals stay GATE_OPEN, so the producer keeps
    submitting into rings nobody will ever drain.  The extended
    fastlane-park-gate row reads every closed lane's rings directly
    and must fire."""
    from ...runtime import fastlane as FL
    orig = FL.BrokerLane.gate_all

    def lead_only(self, v):
        try:
            self.rings[0].gate_set(v)
        except (OSError, ValueError, ConnectionError):
            pass

    FL.BrokerLane.gate_all = lead_only
    try:
        yield
    finally:
        FL.BrokerLane.gate_all = orig


# ---------------------------------------------------------------------------
# Cluster-engine seeds (the federation coordinator's placement ledger,
# runtime/cluster.py).  The canned ledger is recorded PRISTINE (see
# run_seed) — these patch only the REPLAY, like the crash seeds.
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def _seed_cluster_release_dropped() -> Iterator[None]:
    """The cluster replay arm loses 'crelease': a released grant
    resurrects at recovery, and the canned session's re-grant of the
    freed chip turns into a DOUBLE-GRANTED chip — the exact
    conservation break the cluster ledger exists to ban."""
    from ...runtime import cluster as CL
    orig = CL.cluster_apply_record

    def skip_release(state: Any, rec: Any) -> None:
        if rec.get("op") == "crelease":
            return
        orig(state, rec)

    CL.cluster_apply_record = skip_release
    try:
        yield
    finally:
        CL.cluster_apply_record = orig


@contextlib.contextmanager
def _seed_cluster_lossy_migration() -> Iterator[None]:
    """The cmigrate COMMIT replay arm silently drops one chip of the
    sharded grant (placement and node ledger both, so the internal
    books still balance): the recovered placement falls short of the
    journaled target — cross-node migration stopped conserving."""
    from ...runtime import cluster as CL
    orig = CL.cluster_apply_record

    def lossy(state: Any, rec: Any) -> None:
        orig(state, rec)
        if rec.get("op") == "cmigrate" and rec.get("phase") == "commit":
            tenant = str(rec.get("tenant"))
            p = (state.get("placements") or {}).get(tenant)
            if p and len(p.get("chips") or []) > 1:
                lost = p["chips"].pop()
                per = (state.get("used") or {}).get(p["node"]) or {}
                if per.get(str(lost)) == tenant:
                    per.pop(str(lost), None)

    CL.cluster_apply_record = lossy
    try:
        yield
    finally:
        CL.cluster_apply_record = orig


SEEDS: Tuple[Seed, ...] = (
    Seed("broken-lease-refund", "interleave", "token-conservation",
         "batch_pipeline", _seed_broken_refund),
    Seed("dropped-wake", "interleave", "no-lost-wake",
         "batch_pipeline", _seed_dropped_wake),
    Seed("double-ledger-release", "interleave", "region-safety",
         "batch_pipeline", _seed_double_release),
    Seed("teardown-hbm-leak", "interleave", "hbm-ledger-balance",
         "batch_pipeline", _seed_cleanup_leak),
    Seed("lease-overburn", "interleave", "lease-nonnegative",
         "contention", _seed_lease_overburn),
    Seed("unflushed-deferred-journal", "interleave", "reply-durability",
         "tenant_crash", _seed_unflushed_journal),
    Seed("terminal-deferred-leftover", "interleave", "deferred-flush",
         "batch_pipeline", _seed_unflushed_journal),
    Seed("credit-minted-from-nothing", "interleave", "credit-bounds",
         "burst_credits", _seed_credit_mint_nothing),
    Seed("floor-violated-under-burst", "interleave", "floor-under-burst",
         "burst_floor", _seed_floor_violated),
    Seed("fastlane-park-ignored", "interleave", "fastlane-park-gate",
         "fastlane_gate", _seed_fastlane_park_ignored),
    Seed("fastlane-chip1-gate-skipped", "interleave",
         "fastlane-park-gate", "fastlane_multichip",
         _seed_gate_close_lead_only),
    Seed("shed-of-floor-demander", "interleave", "shed-precedence",
         "overload_shed", _seed_shed_floor_demander),
    Seed("skipped-replay-arm", "crash", "replay-ground-truth",
         "", _seed_skipped_replay_arm),
    Seed("nondeterministic-replay", "crash", "replay-deterministic",
         "", _seed_nondeterministic_replay),
    Seed("grant-not-reseeded", "crash", "resume-consistent",
         "", _seed_grant_not_reseeded),
    Seed("lossy-boot-snapshot", "crash", "reresume-idempotent",
         "", _seed_lossy_snapshot),
    Seed("overdropped-torn-tail", "crash", "torn-tail-dropped",
         "", _seed_overdropped_tail),
    Seed("corruption-swallowed", "crash", "corruption-fails-closed",
         "", _seed_corruption_swallowed),
    Seed("lossy-migration", "crash", "migrate-conserves-ledger",
         "", _seed_lossy_migration),
    Seed("diverging-stream-apply", "crash", "replication-lag-bounded",
         "", _seed_diverging_stream_apply),
    Seed("torn-stream-applied", "crash", "repl-torn-never-applied",
         "", _seed_torn_stream_applied),
    Seed("unfenced-stale-primary", "crash", "fenced-epoch-never-acks",
         "", _seed_unfenced_stale_primary),
    Seed("cluster-release-dropped", "cluster",
         "cluster-grant-conservation", "",
         _seed_cluster_release_dropped),
    Seed("cluster-lossy-migration", "cluster",
         "migrate-conserves-ledger-cross-node", "",
         _seed_cluster_lossy_migration),
    Seed("cluster-unfenced-stale-coordinator", "cluster",
         "fenced-stale-coordinator-never-acks", "",
         _seed_unfenced_stale_primary),
)


def run_seed(seed: Seed, record_dir: Optional[str] = None,
             max_schedules: int = 300) -> Tuple[bool, List[str]]:
    """Apply one seed and run its engine; returns (caught, violations).
    ``caught`` is True when the expected invariant fired.  Cluster
    seeds record their canned ledger PRISTINE (before the patch lands)
    — seeds break recovery, never the recording."""
    cluster_rec: Optional[str] = None
    if seed.engine == "cluster":
        cluster_rec = tempfile.mkdtemp(prefix="vtpu-mc-clrec-")
        rec_violations = clustercut.record_cluster_session(cluster_rec)
        if rec_violations:
            raise RuntimeError(
                f"cluster recording not clean: {rec_violations}")
    try:
        with seed.patch():
            if seed.engine == "interleave":
                stats = interleave.explore_scenario(
                    scenarios.get(seed.scenario),
                    max_schedules=max_schedules)
                violations = stats.violations
            elif seed.engine == "cluster":
                stats = clustercut.explore(record_dir=cluster_rec)
                violations = stats.violations
            else:
                stats = crashcut.explore(record_dir=record_dir)
                violations = stats.violations
    finally:
        if cluster_rec is not None:
            shutil.rmtree(cluster_rec, ignore_errors=True)
    tag = f"[{seed.invariant}]"
    return any(tag in v for v in violations), violations


def run_all(max_schedules: int = 300) -> List[Tuple[Seed, bool, int]]:
    """The full matrix.  The crash recording is made ONCE with the
    pristine code (seeds patch recovery, not recording) and reused."""
    results: List[Tuple[Seed, bool, int]] = []
    with tempfile.TemporaryDirectory(prefix="vtpu-mc-selfcheck-") as tmp:
        rec = os.path.join(tmp, "recording")
        os.makedirs(rec)
        rec_violations = crashcut.record_session(rec)
        if rec_violations:
            raise RuntimeError(
                f"selfcheck recording not clean: {rec_violations}")
        for seed in SEEDS:
            caught, violations = run_seed(seed, record_dir=rec,
                                          max_schedules=max_schedules)
            results.append((seed, caught, len(violations)))
    return results
