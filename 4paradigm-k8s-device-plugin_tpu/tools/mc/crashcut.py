"""vtpu-mc crash-cut engine: journal truncation at every record
boundary + recovery replay through the REAL broker code.

A canned multi-tenant session is first RECORDED: a driver task replays
scripted wire frames through the genuine ``TenantSession`` loop (HELLO
/ PUT incl. an oversubscribed spill / COMPILE / EXECUTE with zero-RT
free / DELETE / teardown-close) against the MC harness, so the journal
on disk is byte-for-byte what a real broker under that workload would
have written — bind, put, del, compile, ema, close, epoch, chip and
wedge records all present, one tenant closed and one (multi-chip) left
live.

The journal is then CUT:

  - at EVERY record boundary (the crash-anywhere property), and
  - MID-record at every boundary + a torn fragment (the kill -9
    artifact a CRC'd tail must drop), and
  - with a flipped byte in a NON-tail record (must fail closed), and
  - with a corrupted snapshot after compaction (must fail closed).

Each prefix is recovered through the real ``Journal.load_state`` +
``RuntimeState._recover_from_journal`` + ``try_resume`` — twice, for
replay determinism; against an INDEPENDENT record interpreter
(``_predict``), for ground truth (a skipped or wrong replay arm in
``_apply_record`` diverges from the independent reading); and then
crashed AGAIN immediately after the recovery boot-sequence writes
(epoch record + boot snapshot) and recovered a third time, for
re-resume idempotence.  Violations surface through the invariant
registry (invariants.py, engine="crash").
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from . import invariants as inv_registry
from . import sched as mcsched
from .harness import Harness, ScriptSock, fake_blob

CANNED_CHIPS = 2


# ---------------------------------------------------------------------------
# Recording: the canned session
# ---------------------------------------------------------------------------

def _canned_frames_a() -> List[bytes]:
    import numpy as np
    from ...runtime import protocol as P
    a1 = np.arange(8, dtype=np.float32)          # 32 B — fits the quota
    big = np.zeros(128, dtype=np.float32)        # 512 B — spills (256 B cap)
    return [
        P.frame_header({"kind": P.HELLO, "tenant": "A", "priority": 1,
                        "hbm_limit": 256, "core_limit": 50,
                        "oversubscribe": True, "pid": os.getpid()}),
        P.frame_header({"kind": P.PUT, "id": "w1", "shape": [8],
                        "dtype": "float32", "data": a1.tobytes()}),
        P.frame_header({"kind": P.PUT, "id": "big", "shape": [128],
                        "dtype": "float32", "data": big.tobytes()}),
        P.frame_header({"kind": P.COMPILE, "id": "p",
                        "exported": fake_blob(1, 64)}),
        P.frame_header({"kind": P.EXECUTE, "exe": "p", "args": ["w1"],
                        "outs": ["o1"]}),
        P.frame_header({"kind": P.STATS}),
        P.frame_header({"kind": P.EXECUTE, "exe": "p", "args": ["o1"],
                        "outs": ["o2"], "free": ["w1"]}),
        P.frame_header({"kind": P.STATS}),
        P.frame_header({"kind": P.DELETE, "id": "big"}),
    ]


def _canned_frames_b() -> List[bytes]:
    import numpy as np
    from ...runtime import protocol as P
    wb = np.ones(16, dtype=np.float32)           # 64 B
    return [
        P.frame_header({"kind": P.HELLO, "tenant": "B", "priority": 1,
                        "devices": [0, 1], "hbm_limit": 4096,
                        "core_limit": 30, "pid": os.getpid()}),
        P.frame_header({"kind": P.PUT, "id": "wb", "shape": [16],
                        "dtype": "float32", "data": wb.tobytes()}),
        P.frame_header({"kind": P.COMPILE, "id": "q",
                        "exported": fake_blob(1, 32)}),
        P.frame_header({"kind": P.EXECUTE, "exe": "q", "args": ["wb"],
                        "outs": ["y1"]}),
        P.frame_header({"kind": P.STATS}),
        P.frame_header({"kind": P.EXECUTE, "exe": "q", "args": ["y1"],
                        "outs": ["y2"]}),
        P.frame_header({"kind": P.STATS}),
    ]


def _canned_frames_c() -> List[bytes]:
    import numpy as np
    from ...runtime import protocol as P
    wc = np.full(16, 3.0, dtype=np.float32)      # 64 B
    return [
        P.frame_header({"kind": P.HELLO, "tenant": "C", "priority": 1,
                        "device": 0, "hbm_limit": 4096,
                        "core_limit": 40, "pid": os.getpid()}),
        P.frame_header({"kind": P.PUT, "id": "wc", "shape": [16],
                        "dtype": "float32", "data": wc.tobytes()}),
    ]


def _setup_canned(h: Harness, sched: mcsched.Scheduler) -> None:
    """One sequential driver task: session A runs its full life through
    the REAL handle() loop (incl. the teardown close record), then
    session B binds a two-chip grant and is left LIVE, and session C
    binds single-chip and is live-MIGRATED chip0 -> chip1 through the
    real admin arm — so every cut prefix recovers a mix of closed,
    open, resized and migrated tenants."""
    def driver() -> None:
        from ...runtime import protocol as P
        jr = h.state.journal
        # The two boot-sequence writes RuntimeState.__init__ performs
        # (the harness builds the state piecewise, so the driver issues
        # them — same record shapes, same order).
        jr.append({"op": "epoch", "epoch": h.state.epoch})
        jr.append({"op": "chip", "index": 0, "lat_us": 111.0})
        sess_a = h.session(ScriptSock(_canned_frames_a()))
        sess_a.handle()
        sock_b = ScriptSock(_canned_frames_b())
        sess_b = h.session(sock_b)
        box: List[Any] = [None]
        sess_b._serve(sock_b, box)      # no teardown: B stays live
        # Live quota resize of the still-open tenant, through the REAL
        # AdminSession arm: the journaled `resize` record now sits
        # between B's state records and the wedge — so EVERY cut from
        # here on must recover B with the POST-resize grant (ISSUE 7
        # satellite: resize survives every journal cut).
        adm = h.admin([P.frame_header(
            {"kind": P.RESIZE, "tenant": "B", "hbm_limit": 8192,
             "core_limit": 20})])
        adm.handle()
        # Session C: single-chip tenant with one charged array, then a
        # LIVE MIGRATION chip0 -> chip1 through the real MIGRATE arm
        # (ISSUE 13): every cut past the migrate record must recover C
        # on the NEW chip with the charge books conserved exactly —
        # the migrate-conserves-ledger row.
        sock_c = ScriptSock(_canned_frames_c())
        sess_c = h.session(sock_c)
        box_c: List[Any] = [None]
        sess_c._serve(sock_c, box_c)    # no teardown: C stays live
        adm2 = h.admin([P.frame_header(
            {"kind": P.MIGRATE, "tenant": "C", "device": 1})])
        adm2.handle()
        # A claim-watchdog wedge record (runtime/server.py
        # wedge_report's dying words) closes the log.
        jr.append({"op": "wedge", "stage": "mc-canned",
                   "ts": h.clock.time(), "diagnosis": "seeded wedge"})

    sched.spawn(driver, "driver")


def record_session(jdir: str) -> List[str]:
    """Record the canned session's journal into ``jdir``; returns the
    scheduler/harness violations (must be empty for a usable
    recording)."""
    sched = mcsched.Scheduler()
    with mcsched.patched_modules(sched):
        from ...runtime.journal import Journal
        journal = Journal(jdir, snapshot_every=100_000, fsync=False)
        h = Harness(sched, journal=journal, n_chips=CANNED_CHIPS)
        _setup_canned(h, sched)

        def choose(step: int, enabled: List[mcsched.MCTask]
                   ) -> mcsched.MCTask:
            # Deterministic default policy: stay on the current task,
            # else lowest id — the same rule the explorer's replay
            # uses, so the recording is reproducible byte-for-byte.
            prev = getattr(choose, "prev", None)
            by_id = {t.tid: t for t in enabled}
            pick = prev if prev in by_id else min(by_id)
            choose.prev = pick
            return by_id[pick]

        sched.run(choose)
        violations = list(sched.violations)
        if not violations:
            violations.extend(
                inv_registry.run_checks("interleave", "terminal", h))
        journal.close()
    return violations


# ---------------------------------------------------------------------------
# Record framing (independent of runtime/journal.py on purpose)
# ---------------------------------------------------------------------------

def split_records(data: bytes) -> List[Tuple[int, int, Dict[str, Any]]]:
    """[(start, end, record)] for every complete CRC-framed line —
    parsed HERE, independently, so the cut points and the ground-truth
    interpreter share no code with the implementation under test."""
    out: List[Tuple[int, int, Dict[str, Any]]] = []
    off = 0
    while off < len(data):
        nl = data.find(b"\n", off)
        if nl < 0:
            break
        line = data[off:nl]
        crc_hex, _, payload = line.partition(b" ")
        if int(crc_hex, 16) != zlib.crc32(payload):
            raise ValueError(f"recording has a bad CRC at offset {off}")
        out.append((off, nl + 1, json.loads(payload)))
        off = nl + 1
    return out


def _predict(records: List[Dict[str, Any]],
             default_hbm: int, default_core: int) -> Dict[str, Any]:
    """Independent interpretation of a record prefix: what a correct
    recovery MUST reconstruct.  Deliberately re-implemented from the
    docs/BROKER_RECOVERY.md contract, not from ``_apply_record`` — a
    skipped or wrong replay arm shows up as a divergence."""
    epoch: Optional[str] = None
    tenants: Dict[str, Dict[str, Any]] = {}
    for rec in records:
        op = rec.get("op")
        if op == "epoch":
            epoch = rec.get("epoch")
        elif op == "bind":
            t = tenants.setdefault(rec["name"], {
                "arrays": {}, "exes": {}, "ema": {}, "execs": 0})
            t.update({k: rec.get(k) for k in
                      ("devices", "slots", "priority", "over", "hbm",
                       "core", "spill", "pid", "pidns")})
        elif op == "close":
            tenants.pop(rec.get("name"), None)
        elif op == "put" and rec.get("name") in tenants:
            tenants[rec["name"]]["arrays"][rec["id"]] = {
                "charges": [tuple(c) for c in rec.get("charges") or []],
                "nbytes": 0 if rec.get("spilled")
                else int(rec.get("nbytes", 0)),
            }
        elif op == "del" and rec.get("name") in tenants:
            tenants[rec["name"]]["arrays"].pop(rec.get("id"), None)
        elif op == "compile" and rec.get("name") in tenants:
            tenants[rec["name"]]["exes"][rec["id"]] = rec.get("sha")
        elif op == "resize" and rec.get("name") in tenants:
            # Live resize: the post-resize grant is what recovery must
            # re-seed (docs/BROKER_RECOVERY.md).
            if rec.get("hbm") is not None:
                tenants[rec["name"]]["hbm"] = rec["hbm"]
            if rec.get("core") is not None:
                tenants[rec["name"]]["core"] = rec["core"]
        elif op == "migrate" and rec.get("name") in tenants:
            # Live migration (docs/FAILOVER.md): the post-migrate
            # placement is what recovery must re-seed; the arrays (and
            # their positional charges) are CONSERVED by construction
            # in this independent reading — a replay arm that loses or
            # re-books them diverges.
            if rec.get("devices") is not None:
                tenants[rec["name"]]["devices"] = rec["devices"]
            if rec.get("slots") is not None:
                tenants[rec["name"]]["slots"] = rec["slots"]
            if rec.get("hbm") is not None:
                tenants[rec["name"]]["hbm"] = rec["hbm"]
        elif op == "ema" and rec.get("name") in tenants:
            tenants[rec["name"]]["ema"][rec["key"]] = rec.get("ema")
            if rec.get("execs") is not None:
                tenants[rec["name"]]["execs"] = rec["execs"]
    out: Dict[str, Any] = {}
    for name, t in tenants.items():
        hbm = t.get("hbm") or []
        ndev = len(t.get("devices") or [0])
        out[name] = {
            "devices": [int(d) for d in t.get("devices") or [0]],
            "slots": [int(s) for s in t.get("slots") or []],
            "priority": int(t.get("priority", 1)),
            "over": bool(t.get("over", False)),
            "grant": {
                "hbm": [int(hbm[k]) if k < len(hbm) and hbm[k] is not None
                        else default_hbm for k in range(ndev)],
                "core": int(t["core"]) if t.get("core") is not None
                else default_core,
            },
            "charges": {aid: sorted(tuple(c) for c in am["charges"])
                        for aid, am in t["arrays"].items()},
            "nbytes": {aid: am["nbytes"]
                       for aid, am in t["arrays"].items()},
            "exes": dict(t["exes"]),
            "ema": {k: float(v) for k, v in t["ema"].items()},
            "execs": int(t["execs"]),
            "lease_us": 0.0,
        }
    return {"epoch": epoch, "tenants": out}


def _stream_digest(state: Dict[str, Any], default_hbm: int,
                   default_core: int) -> Dict[str, Any]:
    """A standby's applied state dict (snapshot shape) rendered into
    the SAME digest shape ``_predict`` emits, so the replication-stream
    cuts are judged against the independent interpreter exactly like
    recovery is."""
    out: Dict[str, Any] = {}
    for name, t in (state.get("tenants") or {}).items():
        hbm = t.get("hbm") or []
        ndev = len(t.get("devices") or [0])
        arrays = t.get("arrays") or {}
        out[name] = {
            "devices": [int(d) for d in t.get("devices") or [0]],
            "slots": [int(s) for s in t.get("slots") or []],
            "priority": int(t.get("priority", 1)),
            "over": bool(t.get("over", False)),
            "grant": {
                "hbm": [int(hbm[k]) if k < len(hbm) and hbm[k] is not None
                        else default_hbm for k in range(ndev)],
                "core": int(t["core"]) if t.get("core") is not None
                else default_core,
            },
            "charges": {aid: sorted(tuple(c)
                                    for c in am.get("charges") or [])
                        for aid, am in arrays.items()},
            "nbytes": {aid: (0 if am.get("spilled")
                             else int(am.get("nbytes", 0)))
                       for aid, am in arrays.items()},
            "exes": dict(t.get("exes") or {}),
            "ema": {k: float(v)
                    for k, v in (t.get("ema") or {}).items()},
            "execs": int(t.get("execs", 0)),
            "lease_us": 0.0,
        }
    return {"epoch": state.get("epoch"), "tenants": out}


# ---------------------------------------------------------------------------
# Recovery of one cut
# ---------------------------------------------------------------------------

class _Recovered:
    """One recovery of one cut directory: the harness + journal it ran
    on, kept open so the re-resume step can write through it."""

    def __init__(self, h: Harness, journal: Any) -> None:
        self.h = h
        self.journal = journal

    def digest(self) -> Dict[str, Any]:
        st = self.h.state
        tenants: Dict[str, Any] = {}
        for name, (t, _dl) in st.recovered.items():
            grant = t.grant or {}
            tenants[name] = {
                "devices": [c.index for c in t.chips],
                "slots": list(t.slots),
                "priority": t.priority,
                "over": t.oversubscribe,
                "grant": {
                    "hbm": [int(x) for x in grant.get("hbm") or []],
                    "core": int(grant.get("core"))
                    if grant.get("core") is not None else None,
                },
                "charges": {aid: sorted(tuple(c) for c in charges)
                            for aid, charges in t.charges.items()},
                "nbytes": dict(t.nbytes),
                "exes": dict(t.exe_shas),
                "ema": {k: float(v) for k, v in t.cost_ema.items()},
                "execs": t.executions,
                "lease_us": float(t.lease_us),
            }
        return {"epoch": st.prev_epoch, "tenants": tenants}

    def close(self) -> None:
        self.journal.close()


def recover_cut(cutdir: str, n_chips: int = CANNED_CHIPS) -> _Recovered:
    """Drive the REAL recovery path over one cut journal: load_state +
    _recover_from_journal on a fresh broker stub (inert shims — no
    threads, no schedule exploration; recovery is sequential code).
    Raises JournalCorrupt exactly when the real broker would
    quarantine."""
    inert = mcsched.InertScheduler()
    with mcsched.patched_modules(inert):
        from ...runtime.journal import Journal
        journal = Journal(cutdir, snapshot_every=100_000, fsync=False)
        try:
            state = journal.load_state()
        except Exception:
            journal.close()
            raise
        h = Harness(inert, journal=journal, n_chips=n_chips)
        st = h.state
        st._journal_state = state
        if state is not None:
            st.prev_epoch = state.get("epoch")
            st._recover_from_journal()
        return _Recovered(h, journal)


def _resume_checks(rec: _Recovered) -> List[str]:
    """Resume safety of one recovered state: region limits re-seeded to
    the journaled grant, ledgers equal to the re-applied charge books,
    buckets re-seeded (journal-replay lease reclamation), and the
    resume HELLO path (try_resume) restores arrays/programs
    consistently."""
    out: List[str] = []
    st = rec.h.state
    for name, (t, _dl) in list(st.recovered.items()):
        grant = t.grant or {}
        hbm = grant.get("hbm") or []
        for k, (chip, slot) in enumerate(zip(t.chips, t.slots)):
            r = chip.region
            want_hbm = (int(hbm[k]) if k < len(hbm) and hbm[k] is not None
                        else st.default_hbm)
            if r.limit[slot] != want_hbm:
                out.append(
                    f"tenant {name!r} chip{chip.index}/{slot}: region "
                    f"limit {r.limit[slot]} != journaled grant "
                    f"{want_hbm}")
            want_core = (int(grant["core"])
                         if grant.get("core") is not None
                         else st.default_core)
            if r.core[slot] != want_core:
                out.append(
                    f"tenant {name!r} chip{chip.index}/{slot}: core "
                    f"limit {r.core[slot]} != journaled {want_core}")
            want_used = sum(nb for charges in t.charges.values()
                            for pos, nb in charges
                            if t.chips[pos] is chip
                            and t.slots[pos] == slot)
            if r.used[slot] != want_used:
                out.append(
                    f"tenant {name!r} chip{chip.index}/{slot}: region "
                    f"ledger {r.used[slot]}B != recovered charge book "
                    f"{want_used}B")
            if abs(r.level[slot] - r.cap_us) > 1e-6:
                out.append(
                    f"tenant {name!r} chip{chip.index}/{slot}: bucket "
                    f"not re-seeded at recovery (level "
                    f"{r.level[slot]:.0f} != cap {r.cap_us:.0f})")
        if t.lease_us != 0.0:
            out.append(f"tenant {name!r}: recovered with a nonzero "
                       f"rate lease ({t.lease_us}us) — the replay "
                       f"reclamation must start leases at zero")
    # Resume HELLO adoption: every parked tenant must restore its
    # journaled arrays (or release the unrestorable ones) + programs.
    for name in list(st.recovered):
        t = st.recovered[name][0]
        want_arrays = dict(t.blob_meta)
        adopted = st.try_resume(name, st.prev_epoch)
        if adopted is None:
            out.append(f"tenant {name!r}: try_resume refused its own "
                       f"prev-epoch resume")
            continue
        for aid, am in want_arrays.items():
            spilled = bool(am.get("spilled"))
            with adopted.mu:
                present = (aid in adopted.host_arrays if spilled
                           else aid in adopted.arrays)
            if not present and aid in adopted.charges:
                out.append(
                    f"tenant {name!r}: array {aid!r} neither restored "
                    f"nor released at resume (ledger still charged)")
        for eid in t.exe_shas:
            if eid not in adopted.executables:
                out.append(f"tenant {name!r}: program {eid!r} not "
                           f"restored at resume")
    return out


# ---------------------------------------------------------------------------
# The engine
# ---------------------------------------------------------------------------

@dataclass
class CutContext:
    """What the invariant registry's crash checks read for one cut."""
    label: str
    state_a: Dict[str, Any]
    state_b: Dict[str, Any]
    expected: Optional[Dict[str, Any]] = None
    resume_violations: List[str] = field(default_factory=list)
    reresume_violations: List[str] = field(default_factory=list)
    torn_violations: List[str] = field(default_factory=list)
    corrupt_violations: List[str] = field(default_factory=list)
    # vtpu-failover rows (docs/FAILOVER.md): replication-stream cuts,
    # migrate conservation, epoch fencing.
    repl_violations: List[str] = field(default_factory=list)
    repl_torn_violations: List[str] = field(default_factory=list)
    migrate_violations: List[str] = field(default_factory=list)
    fence_violations: List[str] = field(default_factory=list)

    @staticmethod
    def tenant_digest(state: Dict[str, Any]) -> Dict[str, Any]:
        return state.get("tenants", {})


@dataclass
class CrashStats:
    records: int = 0
    boundary_cuts: int = 0
    torn_cuts: int = 0
    corrupt_checks: int = 0
    repl_cuts: int = 0
    fence_checks: int = 0
    violations: List[str] = field(default_factory=list)


def _make_cut(src_dir: str, dst_dir: str, log_bytes: bytes) -> None:
    shutil.copytree(src_dir, dst_dir)
    from ...runtime.journal import LOG_NAME
    with open(os.path.join(dst_dir, LOG_NAME), "wb") as f:
        f.write(log_bytes)


def explore(record_dir: Optional[str] = None,
            workdir: Optional[str] = None) -> CrashStats:
    """Run the full crash-cut exploration.  ``record_dir``: reuse an
    existing recording (tests; seeded-violation runs) instead of
    recording fresh."""
    from ...runtime.journal import LOG_NAME, JournalCorrupt
    stats = CrashStats()
    tmp = workdir or tempfile.mkdtemp(prefix="vtpu-mc-crash-")
    own_tmp = workdir is None
    try:
        jdir = record_dir or os.path.join(tmp, "recording")
        if record_dir is None:
            os.makedirs(jdir, exist_ok=True)
            rec_violations = record_session(jdir)
            if rec_violations:
                stats.violations.extend(
                    f"[recording] {v}" for v in rec_violations)
                return stats
        with open(os.path.join(jdir, LOG_NAME), "rb") as f:
            log = f.read()
        records = split_records(log)
        stats.records = len(records)
        boundaries = [0] + [end for _s, end, _r in records]
        migrate_idx = next((k for k, (_s, _e, r) in enumerate(records)
                            if r.get("op") == "migrate"), None)

        def _migrate_checks(ctx: "CutContext", i: int) -> None:
            """migrate-conserves-ledger: every cut PAST the migrate
            record must recover the tenant on the journaled target
            placement with its charge books conserved exactly (judged
            against the independent interpreter, whose migrate arm
            conserves by construction)."""
            if migrate_idx is None or i <= migrate_idx:
                return
            mrec = records[migrate_idx][2]
            mname = mrec.get("name")
            got = ctx.state_a["tenants"].get(mname)
            want = (ctx.expected or {}).get(mname)
            if got is None or want is None:
                ctx.migrate_violations.append(
                    f"cut {ctx.label}: migrated tenant {mname!r} lost "
                    f"at recovery")
                return
            if got.get("devices") != mrec.get("devices") or \
                    got.get("slots") != mrec.get("slots"):
                ctx.migrate_violations.append(
                    f"cut {ctx.label}: migrated tenant {mname!r} "
                    f"recovered on {got.get('devices')}/"
                    f"{got.get('slots')} instead of the journaled "
                    f"post-migrate placement {mrec.get('devices')}/"
                    f"{mrec.get('slots')}")
            got_total = sum(nb for ch in got.get("charges", {}).values()
                            for _p, nb in ch)
            want_total = sum(nb for ch in want.get("charges",
                                                   {}).values()
                             for _p, nb in ch)
            if got_total != want_total:
                ctx.migrate_violations.append(
                    f"cut {ctx.label}: migration did not conserve the "
                    f"ledger: recovered {got_total}B of charges vs "
                    f"the independent reading's {want_total}B")

        def _labels(i: int) -> str:
            if i == 0:
                return "boundary[0]=<empty>"
            _s, _e, r = records[i - 1]
            what = r.get("name") or r.get("id") or r.get("index", "")
            return f"boundary[{i}]=after-{r.get('op')}:{what}"

        # -- every record boundary ------------------------------------
        for i, off in enumerate(boundaries):
            label = _labels(i)
            cut = os.path.join(tmp, f"cut{i}")
            _make_cut(jdir, cut, log[:off])
            ctx = CutContext(label=label, state_a={}, state_b={})
            rec_a = recover_cut(cut)
            ctx.state_a = rec_a.digest()
            rec_b = recover_cut(cut)
            ctx.state_b = rec_b.digest()
            rec_b.close()
            ctx.expected = _predict(
                [r for _s, _e, r in records[:i]],
                rec_a.h.state.default_hbm,
                rec_a.h.state.default_core)["tenants"]
            _migrate_checks(ctx, i)
            # Resume-safety checks mutate rec_a (try_resume) — digest
            # was taken first.
            ctx.resume_violations = _resume_checks(rec_a)
            # Re-resume: crash again right after the recovery
            # boot-sequence writes (epoch record + boot snapshot — the
            # exact order RuntimeState.__init__ commits them), recover
            # a third time: the parked/live tenants must round-trip.
            st = rec_a.h.state
            rec_a.journal.append({"op": "epoch", "epoch": st.epoch})
            rec_a.journal.write_snapshot(st._snapshot_dict)
            rec_a.close()
            rec_c = recover_cut(cut)
            got = CutContext.tenant_digest(rec_c.digest())
            rec_c.close()
            want = dict(ctx.state_a["tenants"])
            # try_resume moved parked tenants into st.tenants; the
            # boot snapshot carries BOTH parked and live tenants, so
            # the third recovery must still see every one of them.
            if got != want:
                ctx.reresume_violations.append(
                    f"cut {label}: second crash after recovery lost "
                    f"state: {sorted(want)} -> {sorted(got)}")
            stats.violations.extend(
                inv_registry.run_checks("crash", "cut", ctx))
            stats.boundary_cuts += 1
            shutil.rmtree(cut, ignore_errors=True)

        # -- torn tails: a cut MID-record must recover exactly the
        # previous boundary's state (judged against the INDEPENDENT
        # interpreter, so a parser that over- or under-drops cannot
        # vouch for itself) ----------------------------------------
        for i, (start, end, r) in enumerate(records):
            frag = start + max((end - start) // 2, 1)
            label = f"torn[{i}]=mid-{r.get('op')}"
            cut = os.path.join(tmp, f"torn{i}")
            _make_cut(jdir, cut, log[:frag])
            ctx = CutContext(label=label, state_a={}, state_b={})
            try:
                rec_t = recover_cut(cut)
                ctx.state_a = ctx.state_b = rec_t.digest()
                want = _predict([x for _s, _e, x in records[:i]],
                                rec_t.h.state.default_hbm,
                                rec_t.h.state.default_core)["tenants"]
                rec_t.close()
                if CutContext.tenant_digest(ctx.state_a) != want:
                    ctx.torn_violations.append(
                        f"cut {label}: torn tail was not dropped "
                        f"cleanly — recovered state differs from the "
                        f"last complete boundary[{i}]")
            except JournalCorrupt as e:
                ctx.torn_violations.append(
                    f"cut {label}: torn FINAL record must be dropped, "
                    f"not treated as corruption ({e})")
            stats.violations.extend(
                inv_registry.run_checks("crash", "cut", ctx))
            stats.torn_cuts += 1
            shutil.rmtree(cut, ignore_errors=True)

        # -- non-tail damage must fail closed -------------------------
        for case, mutate in (
            ("flip-mid-log", lambda b: _flip_byte(b, records)),
            ("truncate-first-line", lambda b: b[3:]),
        ):
            cut = os.path.join(tmp, f"corrupt-{case}")
            _make_cut(jdir, cut, mutate(log))
            ctx = CutContext(label=f"corrupt[{case}]", state_a={},
                             state_b={})
            try:
                rec_x = recover_cut(cut)
                rec_x.close()
                ctx.corrupt_violations.append(
                    f"corrupt[{case}]: recovery proceeded on non-tail "
                    f"journal damage instead of raising JournalCorrupt")
            except JournalCorrupt:
                pass
            stats.violations.extend(
                inv_registry.run_checks("crash", "cut", ctx))
            stats.corrupt_checks += 1
            shutil.rmtree(cut, ignore_errors=True)

        # Corrupt SNAPSHOT: recover the full log, commit the boot
        # snapshot, damage it, recover again — must fail closed.
        cut = os.path.join(tmp, "corrupt-snapshot")
        _make_cut(jdir, cut, log)
        rec_s = recover_cut(cut)
        st = rec_s.h.state
        rec_s.journal.append({"op": "epoch", "epoch": st.epoch})
        rec_s.journal.write_snapshot(st._snapshot_dict)
        rec_s.close()
        from ...runtime.journal import SNAP_NAME
        snap_path = os.path.join(cut, SNAP_NAME)
        with open(snap_path, "r+b") as f:
            f.seek(2)
            f.write(b"\x00")
        ctx = CutContext(label="corrupt[snapshot]", state_a={},
                         state_b={})
        try:
            rec_y = recover_cut(cut)
            rec_y.close()
            ctx.corrupt_violations.append(
                "corrupt[snapshot]: recovery proceeded on an "
                "unreadable snapshot instead of raising JournalCorrupt")
        except JournalCorrupt:
            pass
        stats.violations.extend(
            inv_registry.run_checks("crash", "cut", ctx))
        stats.corrupt_checks += 1
        shutil.rmtree(cut, ignore_errors=True)

        # -- replication-stream cuts (docs/FAILOVER.md): the recorded
        # WAL doubles as the REPL_SYNC stream.  Cut it at every record
        # boundary (the standby's applied state must equal the
        # independent interpreter's reading), mid-record (the torn
        # fragment defers, is NEVER applied, and the continuation
        # completes it), and with a flipped byte (the whole chunk is
        # refused and nothing past the damage mutates standby state —
        # the re-bootstrap signal, mirroring the WAL's own fail-closed
        # contract) ---------------------------------------------------
        from ...runtime import replication as repl
        d_hbm, d_core = 1 << 20, 50
        for i, off in enumerate(boundaries):
            ctx = CutContext(label=f"repl-{_labels(i)}", state_a={},
                             state_b={})
            st: Dict[str, Any] = {"tenants": {}, "chips": {}}
            try:
                n, left = repl.apply_stream(st, log[:off])
            except repl.StreamCorrupt as e:
                ctx.repl_violations.append(
                    f"cut {ctx.label}: clean boundary prefix refused "
                    f"as corrupt ({e})")
                n, left = 0, b""
            got = _stream_digest(st, d_hbm, d_core)["tenants"]
            want = _predict([r for _s, _e, r in records[:i]],
                            d_hbm, d_core)["tenants"]
            if got != want:
                ctx.repl_violations.append(
                    f"cut {ctx.label}: standby state after {n} "
                    f"streamed records diverges from the independent "
                    f"reading")
            if left:
                ctx.repl_violations.append(
                    f"cut {ctx.label}: a boundary-aligned prefix left "
                    f"{len(left)}B of deferred partial record")
            stats.violations.extend(
                inv_registry.run_checks("crash", "cut", ctx))
            stats.repl_cuts += 1
        for i, (start, end, r) in enumerate(records):
            frag = start + max((end - start) // 2, 1)
            ctx = CutContext(label=f"repl-torn[{i}]=mid-{r.get('op')}",
                             state_a={}, state_b={})
            st2: Dict[str, Any] = {"tenants": {}, "chips": {}}
            try:
                _n, left = repl.apply_stream(st2, log[:frag])
            except repl.StreamCorrupt as e:
                ctx.repl_torn_violations.append(
                    f"cut {ctx.label}: a mid-record chunk boundary "
                    f"must DEFER the fragment, not refuse the stream "
                    f"({e})")
                left = b""
            got = _stream_digest(st2, d_hbm, d_core)["tenants"]
            want = _predict([x for _s, _e, x in records[:i]],
                            d_hbm, d_core)["tenants"]
            if got != want:
                ctx.repl_torn_violations.append(
                    f"cut {ctx.label}: a torn stream record was "
                    f"applied (state diverges from the last complete "
                    f"boundary)")
            # The continuation must complete the deferred fragment.
            try:
                repl.apply_stream(st2, log[frag:end], left)
            except repl.StreamCorrupt as e:
                ctx.repl_torn_violations.append(
                    f"cut {ctx.label}: the continuation of a deferred "
                    f"fragment was refused ({e})")
            else:
                got2 = _stream_digest(st2, d_hbm, d_core)["tenants"]
                want2 = _predict([x for _s, _e, x in records[:i + 1]],
                                 d_hbm, d_core)["tenants"]
                if got2 != want2:
                    ctx.repl_torn_violations.append(
                        f"cut {ctx.label}: the continuation did not "
                        f"complete the deferred record")
            stats.violations.extend(
                inv_registry.run_checks("crash", "cut", ctx))
            stats.repl_cuts += 1
        ctx = CutContext(label="repl-corrupt[flip-mid-log]",
                         state_a={}, state_b={})
        st4: Dict[str, Any] = {"tenants": {}, "chips": {}}
        try:
            repl.apply_stream(st4, _flip_byte(log, records))
            ctx.repl_torn_violations.append(
                "repl-corrupt: a flipped byte in a complete stream "
                "record was applied instead of refused (the standby "
                "must re-bootstrap)")
        except repl.StreamCorrupt:
            pass
        if st4["tenants"]:
            ctx.repl_torn_violations.append(
                "repl-corrupt: a refused stream chunk still mutated "
                "standby state")
        stats.violations.extend(
            inv_registry.run_checks("crash", "cut", ctx))
        stats.repl_cuts += 1

        # -- epoch fencing (docs/FAILOVER.md): after a takeover claims
        # a newer fence generation, the stale primary's check must
        # refuse — and a journal wired to that fence must refuse
        # appends (journal-before-ack means it can never ack) ---------
        from ...runtime.journal import Journal
        ctx = CutContext(label="fence[takeover]", state_a={},
                         state_b={})
        fdir = os.path.join(tmp, "fence")
        os.makedirs(fdir, exist_ok=True)
        fpath = os.path.join(fdir, "sock.fence")
        stale = repl.Fence(fpath, enabled=True)
        stale.claim("old-epoch")
        taker = repl.Fence(fpath, enabled=True)
        taker.claim("new-epoch")
        fired = False
        try:
            stale.check()
        except OSError:
            fired = True
        if not fired:
            ctx.fence_violations.append(
                "a stale primary's fence check passed after a "
                "takeover claimed a newer generation")
        fenced_jr = Journal(os.path.join(fdir, "j"),
                            snapshot_every=100_000, fsync=False)
        fenced_jr.fence = stale.check
        try:
            fenced_jr.append({"op": "chip", "index": 0,
                              "lat_us": 1.0})
            ctx.fence_violations.append(
                "a journal wired to a fenced epoch still accepted an "
                "append (a stale primary could journal — and ack)")
        except OSError:
            pass
        fenced_jr.close()
        try:
            taker.check()
        except OSError:
            ctx.fence_violations.append(
                "the taking-over standby's own fence check refused "
                "its freshly claimed generation")
        stats.violations.extend(
            inv_registry.run_checks("crash", "cut", ctx))
        stats.fence_checks += 1
    finally:
        if own_tmp:
            shutil.rmtree(tmp, ignore_errors=True)
    return stats


def _flip_byte(log: bytes, records: List[Tuple[int, int, dict]]) -> bytes:
    """Flip one payload byte of a NON-final record (mid-log damage —
    the case that must never be silently dropped)."""
    if len(records) < 2:
        raise ValueError("recording too short to corrupt mid-log")
    start, end, _r = records[len(records) // 2]
    pos = start + (end - start) // 2
    return log[:pos] + bytes([log[pos] ^ 0x5A]) + log[pos + 1:]
