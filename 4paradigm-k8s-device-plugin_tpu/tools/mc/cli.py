"""vtpu-mc command line — both engines, budgets, floor gates, selfcheck.

Exploration is fully deterministic (DFS over scheduling decisions; no
randomness anywhere), so CI needs no seed pinning: the same tree + the
same budget flags explore the same schedules.  The CI ``mc`` job prints
the explored-state counts and floor-gates them (``--min-schedules``):
a refactor that silently shrinks the explored space — fewer yield
points, a scenario that stopped spawning a task — fails loudly instead
of shipping a weaker checker.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

from ...utils import logging as log


def _run_interleave(ns: argparse.Namespace) -> Dict[str, Any]:
    from . import interleave, scenarios
    wanted = ([scenarios.get(ns.scenario)] if ns.scenario
              else list(scenarios.SCENARIOS))
    out: Dict[str, Any] = {"scenarios": {}, "schedules": 0,
                           "decisions": 0, "violations": []}
    for sc in wanted:
        stats = interleave.explore_scenario(
            sc, max_schedules=ns.max_schedules,
            preemption_bound=ns.preemption_bound)
        out["scenarios"][sc.name] = {
            "schedules": stats.schedules,
            "decisions": stats.decisions,
            "truncated": stats.truncated,
            "violations": stats.violations,
            "witness": stats.witness,
        }
        out["schedules"] += stats.schedules
        out["decisions"] += stats.decisions
        out["violations"].extend(
            f"{sc.name}: {v}" for v in stats.violations)
    return out


def _run_crash(ns: argparse.Namespace) -> Dict[str, Any]:
    from . import crashcut
    stats = crashcut.explore()
    return {
        "records": stats.records,
        "boundary_cuts": stats.boundary_cuts,
        "torn_cuts": stats.torn_cuts,
        "corrupt_checks": stats.corrupt_checks,
        "repl_cuts": stats.repl_cuts,
        "fence_checks": stats.fence_checks,
        "violations": stats.violations,
    }


def _run_cluster(ns: argparse.Namespace) -> Dict[str, Any]:
    from . import clustercut
    stats = clustercut.explore()
    return {
        "records": stats.records,
        "boundary_cuts": stats.boundary_cuts,
        "torn_cuts": stats.torn_cuts,
        "corrupt_checks": stats.corrupt_checks,
        "fence_checks": stats.fence_checks,
        "violations": stats.violations,
    }


def _run_selfcheck(ns: argparse.Namespace) -> int:
    from . import selfcheck
    results = selfcheck.run_all(max_schedules=ns.max_schedules)
    missed = [s.name for s, caught, _n in results if not caught]
    for seed, caught, n in results:
        mark = "caught" if caught else "MISSED"
        print(f"  seed {seed.name:28s} [{seed.engine:10s}] -> "
              f"{seed.invariant:24s} {mark} ({n} violation(s))")
    if missed:
        print(f"vtpu-mc selfcheck: {len(missed)} seed(s) NOT caught: "
              f"{missed}")
        return 1
    print(f"vtpu-mc selfcheck: all {len(results)} seeded violations "
          f"caught")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="vtpu-mc",
        description="deterministic model checking of broker quota/"
                    "lease/crash-recovery invariants "
                    "(docs/ANALYSIS.md)")
    ap.add_argument("--engine",
                    choices=("interleave", "crash", "cluster", "all"),
                    default="all")
    ap.add_argument("--scenario", default=None,
                    help="run one interleaving scenario by name")
    ap.add_argument("--list", action="store_true",
                    help="list scenarios and invariants, then exit")
    ap.add_argument("--max-schedules", type=int, default=1500,
                    help="schedule budget PER scenario (deterministic "
                         "DFS; default 1500)")
    ap.add_argument("--preemption-bound", type=int, default=2,
                    help="CHESS-style preemption budget per schedule "
                         "(default 2)")
    ap.add_argument("--min-schedules", type=int, default=0,
                    help="fail unless the interleaving engine explored "
                         "at least this many schedules in total (CI "
                         "floor gate)")
    ap.add_argument("--min-cuts", type=int, default=0,
                    help="fail unless the crash engine explored at "
                         "least this many cuts in total (boundary + "
                         "torn + corruption + replication-stream + "
                         "fence; the CI floor covering the "
                         "vtpu-failover crash-cut space)")
    ap.add_argument("--selfcheck", action="store_true",
                    help="run the seeded-violation matrix instead: "
                         "every invariant's checker must catch its "
                         "deliberately broken broker variant")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny budget (a few schedules per scenario + "
                         "the crash engine): the analyze-job wiring "
                         "check, not the real exploration")
    ap.add_argument("--json", action="store_true")
    ns = ap.parse_args(argv)

    # The explorers replay torn/corrupt journals on purpose — silence
    # the broker's expected warnings so real violations stand out.
    import os
    os.environ.setdefault("VTPU_LOG_LEVEL", "0")
    log.refresh_level()

    if ns.list:
        from . import invariants, scenarios
        print("scenarios:")
        for sc in scenarios.SCENARIOS:
            print(f"  {sc.name:18s} {sc.description}")
        print("invariants:")
        for inv in invariants.INVARIANTS:
            print(f"  [{inv.engine:10s}/{inv.phase:8s}] "
                  f"{inv.name:24s} {inv.description}")
        return 0

    if ns.selfcheck:
        return _run_selfcheck(ns)

    if ns.smoke:
        ns.max_schedules = 5

    report: Dict[str, Any] = {}
    violations: List[str] = []
    if ns.engine in ("interleave", "all"):
        report["interleave"] = _run_interleave(ns)
        violations.extend(report["interleave"]["violations"])
    if ns.engine in ("crash", "all"):
        report["crash"] = _run_crash(ns)
        violations.extend(report["crash"]["violations"])
    if ns.engine in ("cluster", "all"):
        report["cluster"] = _run_cluster(ns)
        violations.extend(report["cluster"]["violations"])

    if ns.json:
        print(json.dumps(report, indent=2))
    else:
        il = report.get("interleave")
        if il:
            for name, s in il["scenarios"].items():
                print(f"  interleave {name:18s} schedules={s['schedules']:6d} "
                      f"decisions={s['decisions']:8d}"
                      + (f" truncated={s['truncated']}"
                         if s["truncated"] else ""))
            print(f"  interleave TOTAL: {il['schedules']} schedules, "
                  f"{il['decisions']} decisions")
        cr = report.get("crash")
        if cr:
            print(f"  crash: {cr['records']} records, "
                  f"{cr['boundary_cuts']} boundary cuts, "
                  f"{cr['torn_cuts']} torn cuts, "
                  f"{cr['corrupt_checks']} corruption checks, "
                  f"{cr['repl_cuts']} replication-stream cuts, "
                  f"{cr['fence_checks']} fence checks")
        cl = report.get("cluster")
        if cl:
            print(f"  cluster: {cl['records']} ledger records, "
                  f"{cl['boundary_cuts']} boundary cuts, "
                  f"{cl['torn_cuts']} torn cuts, "
                  f"{cl['corrupt_checks']} corruption checks, "
                  f"{cl['fence_checks']} fence checks")
        for v in violations:
            print(f"VIOLATION: {v}")
        print(f"vtpu-mc: {len(violations)} violation(s)")

    if ns.min_schedules and ns.engine in ("interleave", "all"):
        got = report["interleave"]["schedules"]
        if got < ns.min_schedules:
            print(f"vtpu-mc: explored-state FLOOR MISSED: "
                  f"{got} < --min-schedules {ns.min_schedules} — "
                  f"the explored space silently shrank", file=sys.stderr)
            return 1
    if ns.engine in ("crash", "all") and report["crash"]["records"] \
            and report["crash"]["boundary_cuts"] \
            != report["crash"]["records"] + 1:
        print("vtpu-mc: crash engine did not cover every record "
              "boundary", file=sys.stderr)
        return 1
    if ns.engine in ("cluster", "all") \
            and report["cluster"]["records"] \
            and report["cluster"]["boundary_cuts"] \
            != report["cluster"]["records"] + 1:
        print("vtpu-mc: cluster engine did not cover every ledger "
              "record boundary", file=sys.stderr)
        return 1
    if ns.min_cuts and ns.engine in ("crash", "all"):
        cr = report["crash"]
        total = (cr["boundary_cuts"] + cr["torn_cuts"]
                 + cr["corrupt_checks"] + cr["repl_cuts"]
                 + cr["fence_checks"])
        if total < ns.min_cuts:
            print(f"vtpu-mc: crash-cut FLOOR MISSED: {total} < "
                  f"--min-cuts {ns.min_cuts} — the crash-cut space "
                  f"silently shrank", file=sys.stderr)
            return 1
    return 1 if violations else 0
