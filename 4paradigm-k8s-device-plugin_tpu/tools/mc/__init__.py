"""vtpu-mc — deterministic model checking of the broker's quota, lease
and crash-recovery invariants (docs/ANALYSIS.md "Model checking").

Two engines, both driving the REAL broker code (``runtime/server.py``,
``runtime/journal.py``) — never a re-implementation:

  - **interleave** (interleave.py + sched.py + scenarios.py): the
    broker's lock/queue/wake primitives are rebound to cooperative
    shims whose every operation is a yield point; a DFS with DPOR-style
    sleep sets and a CHESS-style bounded-preemption budget explores the
    schedules of small multi-tenant scenarios, and the invariant
    registry (invariants.py) is checked at every step and at every
    quiescent terminal state.
  - **crash** (crashcut.py): a canned multi-tenant session is recorded
    through the real session/journal paths, then the journal is cut at
    EVERY record boundary (and mid-record, for CRC-torn tails) and the
    real recovery replays each prefix — twice for determinism, against
    an independent record interpreter for ground truth, and re-resumed
    for idempotence.

Run as ``python -m vtpu.tools.mc`` or ``vtpu-smi mc``; CI runs the
``mc`` job under a bounded schedule budget with the explored-state
count floor-gated.  ``--selfcheck`` proves every invariant's checker
still catches its seeded violation.  There is NO suppression mechanism
on purpose: a real violation is fixed in broker source, never waived.
"""

from __future__ import annotations

from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    from . import cli
    return cli.main(argv)
