"""``python -m vtpu.tools.mc`` — see package docstring."""

from . import main

if __name__ == "__main__":
    raise SystemExit(main())
