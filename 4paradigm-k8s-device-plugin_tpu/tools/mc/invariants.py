"""vtpu-mc invariant registry — the single declaration point.

Every property the model checker enforces is declared HERE, as one
``Invariant`` with the engine(s) that check it and the phase it runs
in.  New broker state transitions (cross-node federation, elastic
burst credits — ROADMAP 3-4) land with new entries in this table, not
with new hope; docs/ANALYSIS.md renders the same table for operators.

Phases:

  - ``step``     — checked at every scheduling decision of the
                   interleaving engine (cheap safety: non-negativity,
                   over-credit, lost wakes, deadlock hooks live in the
                   scheduler/harness and surface through these).
  - ``terminal`` — checked once per fully-quiescent explored schedule
                   (conservation equations that only balance when no
                   operation is mid-flight).
  - ``cut``      — checked per journal truncation point by the
                   crash-cut engine (recovery safety).
  - ``litmus``   — checked over every explored weak-memory execution
                   of the vtpu-wmm litmus suite (``tools/wmm``): the
                   shared-region lock-free protocols under C11-ish
                   reordering, not just sequential consistency.
  - ``net``      — checked over every explored network-fault schedule
                   of the vtpu-dmc distributed model checker
                   (``tools/dmc``): the REAL federation coordinator
                   (``runtime/cluster.py``) driven under exhaustive
                   message delay/duplication/reorder/drop and
                   coordinator/node crash-restart.

A check returns a list of human-readable violation strings (empty =
holds).  Its ``ctx`` is the interleaving ``Harness`` for step/terminal
checks, a ``CutContext`` for cut checks, a ``WmmContext``
(``tools/wmm/model.py``) for litmus checks, and a ``World``
(``tools/dmc/world.py``) for net checks.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, List, Tuple

EPS_US = 1.0


@dataclass(frozen=True)
class Invariant:
    name: str
    engine: str        # "interleave" | "crash" | "cluster" | "wmm" | "dmc"
    phase: str         # "step" | "terminal" | "cut" | "litmus" | "net"
    description: str
    check: Callable[[Any], List[str]]


# ---------------------------------------------------------------------------
# Interleaving-engine checks (ctx = tools.mc.harness.Harness)
# ---------------------------------------------------------------------------

def _chk_token_conservation(h: Any) -> List[str]:
    """At a quiescent terminal state with a frozen (refill=False)
    bucket, every µs ever debited is accounted for: it was either
    metered as device time (busy counter) or still sits in a live
    tenant's unexpired rate lease.  A broken refund path (expiry,
    suspend, release, drain) or a double debit breaks the balance —
    quota leaked."""
    if h.refill:
        return []
    out: List[str] = []
    lease_by_slot: dict = {}
    credit_by_slot: dict = {}
    live = list(h.state.tenants.values()) \
        + [e[0] for e in h.state.recovered.values()]
    for t in live:
        for chip, slot in zip(t.chips, t.slots):
            key = (chip.index, slot)
            lease_by_slot[key] = lease_by_slot.get(key, 0.0) \
                + float(t.lease_us)
            # Burst-credit-funded device time never touched the bucket
            # (docs/SCHEDULING.md): it is billed to the credit bank, so
            # the bucket's net debit must fall short of metered busy
            # time by exactly the spent credit — anything else means a
            # lease carried borrowed credit, or a credit admit was
            # double-billed.
            credit_by_slot[key] = credit_by_slot.get(key, 0.0) \
                + float(t.credit_spent_us)
    for chip in h.state.chips.values():
        r = chip.region
        for s in range(r.nslots):
            if r.core[s] <= 0:
                if abs(r.net_debit[s]) > EPS_US:
                    out.append(
                        f"unmetered slot chip{chip.index}/{s} has a "
                        f"net bucket debit of {r.net_debit[s]:.0f}us")
                continue
            leases = lease_by_slot.get((chip.index, s), 0.0)
            credit = credit_by_slot.get((chip.index, s), 0.0)
            expect = r.busy_since_reset(s) + leases - credit
            if abs(r.net_debit[s] - expect) > EPS_US:
                out.append(
                    f"token conservation broken on chip{chip.index} "
                    f"slot {s}: net debit {r.net_debit[s]:.0f}us != "
                    f"busy {r.busy_since_reset(s)}us + outstanding "
                    f"leases {leases:.0f}us - spent credit "
                    f"{credit:.0f}us (quota leak / double credit / "
                    f"credit-funded lease)")
    return out


def _chk_hbm_balance(h: Any) -> List[str]:
    """Region HBM ledgers must equal the sum of the per-tenant charge
    books at every quiescent terminal state — and a slot with no live
    tenant must read zero (release leaks nothing)."""
    out: List[str] = []
    expected = h.expected_hbm()
    for chip in h.state.chips.values():
        r = chip.region
        for s in range(r.nslots):
            want = expected.get((chip.index, s), 0)
            if r.used[s] != want:
                out.append(
                    f"HBM ledger imbalance on chip{chip.index} slot "
                    f"{s}: region says {r.used[s]}B, tenant books say "
                    f"{want}B")
    return out


def _chk_region_safety(h: Any) -> List[str]:
    """Continuous region safety, surfaced by the ModelRegion itself at
    each mutation: the bucket never exceeds its seed (a refund past it
    is a double credit) and the HBM ledger never goes negative (a
    release past zero is a double release)."""
    out: List[str] = []
    for chip in h.state.chips.values():
        out.extend(chip.region.violations)
        chip.region.violations = []
    return out


def _chk_lease_nonneg(h: Any) -> List[str]:
    """A tenant's pre-debited lease balance can never be negative —
    burning more than was granted means unmetered device time."""
    out: List[str] = []
    for t in list(h.state.tenants.values()):
        if t.lease_us < -1e-9:
            out.append(f"tenant {t.name!r} lease balance is negative: "
                       f"{t.lease_us}")
    return out


def _chk_credit_bounds(h: Any) -> List[str]:
    """Burst-credit sanity at every step (docs/SCHEDULING.md): a
    balance can never be negative (spending credit that was never
    banked) nor exceed the burst cap, and a tenant's cumulative mint
    can never exceed its core share of the wall time since bind — the
    'credit minted from nothing' bug class."""
    from ...runtime import server as S
    cap = S.BURST_CAP_US
    out: List[str] = []
    now = h.clock.now()
    seen: set = set()
    every = (list(h.state.tenants.values())
             + [e[0] for e in h.state.recovered.values()]
             + list(h.all_tenants))
    for t in every:
        if id(t) in seen:
            continue
        seen.add(id(t))
        if t.credit_us < -EPS_US:
            out.append(f"tenant {t.name!r} credit balance is negative: "
                       f"{t.credit_us:.0f}us")
        if t.credit_us > cap + EPS_US:
            out.append(f"tenant {t.name!r} credit balance "
                       f"{t.credit_us:.0f}us exceeds the burst cap "
                       f"{cap:.0f}us")
        max_mint = max(now - t.bind_ts, 0.0) * t.core_pct * 1e4 + EPS_US
        if t.credit_minted_us > max_mint:
            out.append(
                f"tenant {t.name!r} minted {t.credit_minted_us:.0f}us "
                f"of credit but its {t.core_pct}% share of the "
                f"{now - t.bind_ts:.3f}s since bind is only "
                f"{max_mint:.0f}us (credit minted from nothing)")
    return out


def _chk_floor_under_burst(h: Any) -> List[str]:
    """Hard-floor guard: no burst-credit spend may ever happen while a
    co-tenant with queued work sits bucket-throttled — the broker logs
    every spend with the contention snapshot it computed, and a spend
    recorded as contended means the guard was bypassed."""
    out: List[str] = []
    for chip in h.state.chips.values():
        for ev in (chip.scheduler.credit_log or ()):
            kind, name, us, contended = ev
            if kind == "spend" and contended:
                out.append(
                    f"tenant {name!r} spent {us:.0f}us of burst credit "
                    f"on chip{chip.index} while floor-demanding "
                    f"co-tenant(s) {list(contended)} were throttled "
                    f"with backlog (hard floor violated under burst)")
    return out


def _chk_shed_precedence(h: Any) -> List[str]:
    """Overload shedding must shed lowest priority first: a priority-0
    (floor-demanding) tenant's request may only ever be refused at the
    hard backlog cap (overload level > 1.0), never while lower
    priorities would still be admitted."""
    out: List[str] = []
    for name, pri, level in (h.state.admission.shed_log or ()):
        if pri <= 0 and level <= 1.0 + 1e-9:
            out.append(
                f"floor-demanding (priority {pri}) tenant {name!r} "
                f"was shed at overload level {level:.2f} — only the "
                f"hard cap (level > 1.0) may refuse priority 0")
    return out


def _chk_fastlane_gate(h: Any) -> List[str]:
    """No execute is admitted through a fastlane ring for a parked
    (admin-suspended or auto-preempted) or released tenant: the
    drainer's admit oracle records the park verdict taken under
    scheduler.mu next to every batch it executed.  Additionally,
    every lane that went through a close transition must have
    published GATE_CLOSED on EVERY chip's ring — a sharded lane
    whose follower ring stays open leaves the producer submitting
    into a ring nobody will ever drain (vtpu-fastlane-everywhere)."""
    hub = getattr(h.state, "fastlane", None)
    log_ = getattr(hub, "admit_log", None) or []
    out = []
    for name, n, parked, closed in log_:
        if n > 0 and (parked or closed):
            out.append(
                f"fastlane: {n} execute(s) admitted through tenant "
                f"{name}'s ring while "
                f"{'parked' if parked else 'released'}")
    for lane in getattr(hub, "mc_closed", None) or []:
        for k, ring in enumerate(lane.rings):
            try:
                g = ring.gate()
            except Exception:  # noqa: BLE001 - closed native handle
                continue
            if g != 2:  # GATE_CLOSED
                out.append(
                    f"fastlane: closed lane of tenant "
                    f"{lane.tenant.name!r} left chip-ordinal {k}'s "
                    f"ring gate at {g} (want GATE_CLOSED on EVERY "
                    f"chip's ring)")
    return out


def _chk_lost_wake(h: Any) -> List[str]:
    out, h.lost_wakes = list(h.lost_wakes), []
    return out


def _chk_durability(h: Any) -> List[str]:
    out, h.durability = list(h.durability), []
    return out


def _chk_deferred_flush(h: Any) -> List[str]:
    """At quiescence every reply has been sent, so every deferred
    journal record must have been flushed — a leftover means some path
    acknowledged (or tore down) state the journal never got."""
    if h.state.journal is None:
        return []
    out: List[str] = []
    seen: set = set()
    every = (list(h.state.tenants.values())
             + [e[0] for e in h.state.recovered.values()]
             + list(h.all_tenants))
    for t in every:
        if id(t) in seen:
            continue
        seen.add(id(t))
        if t.pending_journal:
            out.append(
                f"tenant {t.name!r} ends the scenario with "
                f"{len(t.pending_journal)} deferred journal record(s) "
                f"never flushed (lost durability)")
    return out


# ---------------------------------------------------------------------------
# Weak-memory-engine checks (ctx = tools.wmm.model.WmmContext)
#
# The wmm engine and the litmus ``check`` functions deposit violation
# strings into named buckets as executions are explored; each row
# below drains its bucket.  The indirection keeps the registry the
# single declaration point (docs/ANALYSIS.md renders this table) while
# the detection itself lives with the operational model.
# ---------------------------------------------------------------------------

def _wmm_bucket(row: str) -> Callable[[Any], List[str]]:
    def chk(ctx: Any) -> List[str]:
        return ctx.take(row)
    return chk


# ---------------------------------------------------------------------------
# Crash-cut-engine checks (ctx = tools.mc.crashcut.CutContext)
# ---------------------------------------------------------------------------

def _chk_replay_deterministic(c: Any) -> List[str]:
    if c.state_a != c.state_b:
        return [f"cut {c.label}: two recoveries of the same journal "
                f"prefix disagree (replay is nondeterministic)"]
    return []


def _chk_ground_truth(c: Any) -> List[str]:
    """At a cut on a record boundary of the single-threaded phase, the
    replayed tenant/array/charge state must equal the LIVE broker
    state snapshotted when that record was appended — any skipped or
    wrong replay arm shows up as a diff."""
    if c.expected is None:
        return []
    got = c.tenant_digest(c.state_a)
    if got != c.expected:
        return [f"cut {c.label}: recovered state diverges from the "
                f"live broker state at append time: got {got!r}, "
                f"expected {c.expected!r}"]
    return []


def _chk_resume_consistent(c: Any) -> List[str]:
    """Driving the REAL ``_recover_from_journal`` over the prefix must
    leave every recovered tenant internally consistent: region limits
    re-seeded to the journaled grant, region usage equal to the
    re-applied ledger, and the rate lease starting at zero (the
    journal-replay lease reclamation)."""
    return c.resume_violations


def _chk_reresume_idempotent(c: Any) -> List[str]:
    """Crashing again immediately after recovery (epoch record +
    boot snapshot written, nothing else) and recovering a second time
    must yield the same tenants — resume is idempotent."""
    return c.reresume_violations


def _chk_torn_tail(c: Any) -> List[str]:
    """A cut mid-record (the kill -9 artifact) must recover exactly
    the previous record boundary's state — the torn tail is dropped,
    never guessed at, and never poisons the rest of the log."""
    return c.torn_violations


def _chk_fail_closed(c: Any) -> List[str]:
    """Non-tail corruption must raise JournalCorrupt (quarantine +
    fresh epoch) — recovery never proceeds on a log it cannot trust."""
    return c.corrupt_violations


def _chk_repl_stream(c: Any) -> List[str]:
    """vtpu-failover (docs/FAILOVER.md): a standby applying the
    replication stream through the real _apply_record arms must land
    on exactly the independent interpreter's reading at every record
    boundary — bounded lag, no divergence."""
    return getattr(c, "repl_violations", [])


def _chk_repl_torn(c: Any) -> List[str]:
    """A torn or CRC-damaged stream record is NEVER applied: a
    mid-record chunk defers the fragment (the continuation completes
    it), and a flipped byte refuses the whole chunk so the standby
    re-syncs via snapshot bootstrap — mirroring the WAL's own
    fail-closed contract."""
    return getattr(c, "repl_torn_violations", [])


def _chk_migrate_ledger(c: Any) -> List[str]:
    """Live migration conserves the ledger exactly: every journal cut
    past the migrate record recovers the tenant on the journaled
    target placement with its charge books byte-identical to the
    independent reading (no lost, duplicated or re-booked charges)."""
    return getattr(c, "migrate_violations", [])


def _chk_fenced_epoch(c: Any) -> List[str]:
    """fenced-epoch-never-acks: once a takeover claims a newer fence
    generation, the stale primary's fence check — and therefore every
    journal append, and therefore every journal-before-reply ack —
    must refuse."""
    return getattr(c, "fence_violations", [])


# ---------------------------------------------------------------------------
# Cluster-ledger-engine checks (ctx = tools.mc.clustercut
# .ClusterCutContext) — the federation coordinator's placement ledger
# (runtime/cluster.py, docs/FEDERATION.md) cut at every boundary.  The
# engine deposits into named buckets (the wmm pattern); each row
# drains its own.
# ---------------------------------------------------------------------------

def _chk_cluster_conservation(c: Any) -> List[str]:
    """cluster-grant-conservation: at every crash cut of the
    coordinator's ledger, replay must be deterministic, equal the
    independent docs/FEDERATION.md reading, drop torn tails cleanly,
    fail closed on damage — and the recovered state must satisfy
    ``check_conservation`` exactly: sum of per-node ledgers == the
    cluster placement ledger, no chip granted twice, no placement on
    an unregistered node."""
    return getattr(c, "cluster_violations", [])


def _chk_cluster_migrate(c: Any) -> List[str]:
    """migrate-conserves-ledger-cross-node: a tenant whose prefix ends
    in a journaled cmigrate COMMIT recovers exactly on the journaled
    target node/chips, the target ledger holds precisely those chips,
    and no other node still holds any — source release only after
    target commit, nothing lost or double-granted in the move."""
    return getattr(c, "cmigrate_violations", [])


def _chk_cluster_fence(c: Any) -> List[str]:
    """fenced-stale-coordinator-never-acks: once a successor claims a
    newer fence generation, the stale coordinator's fence check — and
    therefore every ledger append, and therefore every placement ack —
    must refuse."""
    return getattr(c, "cfence_violations", [])


# ---------------------------------------------------------------------------
# DMC network-fault-engine checks (ctx = tools.dmc.world.World) — the
# REAL coordinator driven under exhaustive message fates (deliver /
# delay / duplicate / drop) plus coordinator crash-restart and node
# death.  The world deposits into named buckets as it steps; each row
# drains its own (the wmm pattern).
# ---------------------------------------------------------------------------

def _dmc_bucket(row: str) -> Callable[[Any], List[str]]:
    def chk(ctx: Any) -> List[str]:
        return ctx.take(row)
    return chk


INVARIANTS: Tuple[Invariant, ...] = (
    Invariant(
        "token-conservation", "interleave", "terminal",
        "net bucket debit == metered busy time + outstanding leases "
        "(no quota leak through grant/burn/refund/expiry/suspend/"
        "release/drain)", _chk_token_conservation),
    Invariant(
        "hbm-ledger-balance", "interleave", "terminal",
        "region HBM ledgers == per-tenant charge books; released "
        "slots read zero", _chk_hbm_balance),
    Invariant(
        "region-safety", "interleave", "step",
        "bucket never over-credited past its seed (double refund); "
        "HBM ledger never negative (double release)",
        _chk_region_safety),
    Invariant(
        "lease-nonnegative", "interleave", "step",
        "pre-debited lease balances never go negative",
        _chk_lease_nonneg),
    Invariant(
        "credit-bounds", "interleave", "step",
        "burst-credit balances stay within [0, cap] and cumulative "
        "mint never exceeds the tenant's core share of wall time "
        "since bind (no credit minted from nothing)",
        _chk_credit_bounds),
    Invariant(
        "floor-under-burst", "interleave", "terminal",
        "no burst-credit spend while a co-tenant with queued work is "
        "bucket-throttled (hard floors never violated by bursting)",
        _chk_floor_under_burst),
    Invariant(
        "shed-precedence", "interleave", "terminal",
        "overload shedding refuses lowest priority first; priority 0 "
        "is only ever shed at the hard backlog cap",
        _chk_shed_precedence),
    Invariant(
        "fastlane-park-gate", "interleave", "terminal",
        "no execute is admitted through a fastlane ring for a parked "
        "or released tenant (the ring honors SUSPEND/preemption/"
        "teardown exactly like the brokered queues)",
        _chk_fastlane_gate),
    Invariant(
        "no-lost-wake", "interleave", "step",
        "the dispatcher never idle-sleeps while dispatchable work is "
        "queued", _chk_lost_wake),
    Invariant(
        "reply-durability", "interleave", "step",
        "deferred journal records are flushed before the reply that "
        "acknowledges them", _chk_durability),
    Invariant(
        "deferred-flush", "interleave", "terminal",
        "no deferred journal record survives to quiescence unflushed",
        _chk_deferred_flush),
    Invariant(
        "replay-deterministic", "crash", "cut",
        "recovering the same journal prefix twice yields identical "
        "state", _chk_replay_deterministic),
    Invariant(
        "replay-ground-truth", "crash", "cut",
        "replayed state at every record boundary equals the live "
        "broker state when that record was appended",
        _chk_ground_truth),
    Invariant(
        "resume-consistent", "crash", "cut",
        "epoch resume from any prefix re-seeds grants/limits/ledgers "
        "consistently and restarts leases at zero",
        _chk_resume_consistent),
    Invariant(
        "reresume-idempotent", "crash", "cut",
        "a second crash immediately after recovery recovers the same "
        "tenants", _chk_reresume_idempotent),
    Invariant(
        "torn-tail-dropped", "crash", "cut",
        "a mid-record cut recovers exactly the previous boundary's "
        "state", _chk_torn_tail),
    Invariant(
        "corruption-fails-closed", "crash", "cut",
        "non-tail journal damage raises JournalCorrupt (no guessed "
        "quota state)", _chk_fail_closed),
    Invariant(
        "replication-lag-bounded", "crash", "cut",
        "a standby applying the replication stream through the real "
        "_apply_record arms equals the independent reading at every "
        "record boundary (no divergence, bounded lag)",
        _chk_repl_stream),
    Invariant(
        "repl-torn-never-applied", "crash", "cut",
        "a torn/CRC-damaged stream record is never applied: fragments "
        "defer, damage refuses the chunk and forces a snapshot "
        "re-bootstrap", _chk_repl_torn),
    Invariant(
        "migrate-conserves-ledger", "crash", "cut",
        "live migration recovers on the journaled target placement "
        "with charge books conserved exactly at every cut",
        _chk_migrate_ledger),
    Invariant(
        "fenced-epoch-never-acks", "crash", "cut",
        "after a takeover bumps the fence generation, the stale "
        "primary can never journal (and so never ack) again",
        _chk_fenced_epoch),
    Invariant(
        "cluster-grant-conservation", "cluster", "cut",
        "every crash cut of the coordinator's placement ledger "
        "replays deterministically to the independent reading with "
        "sum of node ledgers == cluster ledger (no double-granted "
        "chip, no ghost placement)", _chk_cluster_conservation),
    Invariant(
        "migrate-conserves-ledger-cross-node", "cluster", "cut",
        "a committed cross-node migration recovers exactly on the "
        "journaled target placement; source released only after "
        "target commit, no chip lost or double-granted in the move",
        _chk_cluster_migrate),
    Invariant(
        "fenced-stale-coordinator-never-acks", "cluster", "cut",
        "after a successor coordinator bumps the fence generation, "
        "the stale coordinator can never journal (and so never ack) "
        "a placement again", _chk_cluster_fence),
    Invariant(
        "wmm-no-torn-payload", "wmm", "litmus",
        "no seqlock/ring reader ever ACCEPTS a torn or stale payload "
        "under any allowed reordering of the declared orders",
        _wmm_bucket("wmm-no-torn-payload")),
    Invariant(
        "wmm-data-race", "wmm", "litmus",
        "no plain (non-atomic) access to shared-region state races a "
        "concurrent write (C11 undefined behavior)",
        _wmm_bucket("wmm-data-race")),
    Invariant(
        "wmm-ledger-conserved", "wmm", "litmus",
        "lock-free ledger charge/free conserves exactly: no lost "
        "update double-admits past the limit or double-frees",
        _wmm_bucket("wmm-ledger-conserved")),
    Invariant(
        "wmm-lease-bounded", "wmm", "litmus",
        "rate-lease burn + revoke refund + residue never exceeds the "
        "one pre-debited quantum (no unmetered device time)",
        _wmm_bucket("wmm-lease-bounded")),
    Invariant(
        "wmm-credit-bounds", "wmm", "litmus",
        "burst-credit bank stays within [0, cap] and spends within "
        "mints under cross-process atomics",
        _wmm_bucket("wmm-credit-bounds")),
    Invariant(
        "wmm-crash-atomic", "wmm", "litmus",
        "degraded-mode quota reads observe old-or-new grants only "
        "(never torn), and the quota still bites with the broker "
        "dead mid-update", _wmm_bucket("wmm-crash-atomic")),
    Invariant(
        "wmm-ring-fifo", "wmm", "litmus",
        "the planned interposer-only execute ring delivers "
        "descriptors in FIFO order, never executes an unpublished "
        "descriptor, and its credit gate never leaks or over-admits",
        _wmm_bucket("wmm-ring-fifo")),
    Invariant(
        "dmc-no-double-grant", "dmc", "net",
        "under any message fate schedule no chip is ever granted to "
        "two tenants at once (per-node free/placed/reserved ledgers "
        "stay disjoint and exact at every step)",
        _dmc_bucket("dmc-no-double-grant")),
    Invariant(
        "dmc-at-least-one-full-copy", "dmc", "net",
        "a placed tenant always has at least one full model copy "
        "(serving, quiesced or parked) on a live node at every step "
        "of any migration/fault schedule — no lost-ack ordering may "
        "pass through a zero-copy window",
        _dmc_bucket("dmc-at-least-one-full-copy")),
    Invariant(
        "dmc-no-orphan-copy", "dmc", "net",
        "at quiescence no node holds a model copy for a tenant whose "
        "ledger placement is elsewhere, except copies whose abort "
        "delivery was dropped by the fault budget (those are owned "
        "by the resume-grace reaper)", _dmc_bucket("dmc-no-orphan-copy")),
    Invariant(
        "dmc-reservation-conservation", "dmc", "net",
        "coordinator check_conservation holds at every step and "
        "after every crash-restart; every acked placement is "
        "durable across coordinator crash; no migration reservation "
        "leaks to quiescence", _dmc_bucket("dmc-reservation-conservation")),
    Invariant(
        "dmc-fenced-coordinator-never-acks", "dmc", "net",
        "after a crash-restart bumps the fence generation, the stale "
        "coordinator instance can never ack a placement again",
        _dmc_bucket("dmc-fenced-coordinator-never-acks")),
    Invariant(
        "dmc-re-drive-idempotence", "dmc", "net",
        "re-delivering any idempotent verb or dance message "
        "(checked by construction on EVERY delivery) leaves "
        "coordinator ledger and node copies bit-identical",
        _dmc_bucket("dmc-re-drive-idempotence")),
)


def for_engine(engine: str, phase: str) -> List[Invariant]:
    return [i for i in INVARIANTS
            if i.engine == engine and i.phase == phase]


def run_checks(engine: str, phase: str, ctx: Any) -> List[str]:
    out: List[str] = []
    for inv in for_engine(engine, phase):
        for v in inv.check(ctx):
            out.append(f"[{inv.name}] {v}")
    return out
