"""vtpu-mc cluster crash-cut engine: the federation coordinator's
placement ledger (runtime/cluster.py, docs/FEDERATION.md) cut at every
record boundary.

A canned cluster session is first RECORDED through the REAL
:class:`~....runtime.cluster.Coordinator` — its journaled mutation
path (``_append``: fence check, CRC frame, apply) and its real
dispatch arms, no sockets needed — so the ledger on disk is
byte-for-byte what a live coordinator under that membership/placement
history would have written:

  - coordinator epoch (``cepoch``), three node joins (4+4+2 chips),
  - pack placements incl. a 2-chip and a 4-chip grant,
  - a release followed by a re-grant of the freed chip,
  - a cross-node migration journaled as the ``cmigrate``
    begin/commit pair the MIGRATE orchestration writes,
  - an ABORTED migration (begin + abort — the ledger must not move),
  - a node death (``node_down``) whose re-placement finds no capacity
    and falls back to releasing the grant,
  - final releases.

The ledger is then CUT exactly like the broker WAL (crashcut.py):
at every record boundary, mid-record (the kill -9 torn tail), and
with non-tail damage (must fail closed).  Each prefix is replayed
TWICE through the real ``Journal.load_state`` +
:func:`~....runtime.cluster.cluster_apply_record` (determinism),
judged against an INDEPENDENT interpreter re-implemented from the
docs/FEDERATION.md record contract (ground truth), audited by
:func:`~....runtime.cluster.check_conservation` (sum of node ledgers
== cluster ledger), and — for every tenant whose prefix ends in a
committed migration — held to exact conservation on the journaled
target placement.  The epoch-fence test mirrors the broker's: a
superseded coordinator's fence check, and any ledger append behind
it, must refuse.

Violations surface through the invariant registry (invariants.py,
engine="cluster"): ``cluster-grant-conservation``,
``migrate-conserves-ledger-cross-node`` and
``fenced-stale-coordinator-never-acks`` drain the buckets this engine
fills, and tools/mc/selfcheck.py proves each row still fires on a
deliberately broken replay.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from . import invariants as inv_registry
from .crashcut import _flip_byte, split_records


@dataclass
class ClusterStats:
    records: int = 0
    boundary_cuts: int = 0
    torn_cuts: int = 0
    corrupt_checks: int = 0
    fence_checks: int = 0
    violations: List[str] = field(default_factory=list)


@dataclass
class ClusterCutContext:
    """Per-cut context handed to the engine="cluster" invariant rows.
    The engine deposits violation strings into the named buckets (the
    wmm pattern): detection lives with the exploration, the registry
    stays the single declaration point."""
    label: str
    state_a: Dict[str, Any]
    state_b: Dict[str, Any]
    expected: Optional[Dict[str, Any]] = None
    cluster_violations: List[str] = field(default_factory=list)
    cmigrate_violations: List[str] = field(default_factory=list)
    cfence_violations: List[str] = field(default_factory=list)


# ---------------------------------------------------------------------------
# Recording: the canned coordinator session
# ---------------------------------------------------------------------------

def record_cluster_session(jdir: str) -> List[str]:
    """Drive a real Coordinator (journal in ``jdir``) through the
    canned membership/placement/migration history.  Returns violation
    strings (dispatch refusals, a dirty final conservation audit) —
    empty on a healthy run."""
    from ...runtime import cluster as CL
    from ...plugin.allocator import cluster_choose_placement

    violations: List[str] = []
    coord = CL.Coordinator(os.path.join(jdir, "cl.sock"), jdir,
                           policy="pack", hb_dead_s=3600.0)

    def ok(rep: Dict[str, Any], what: str) -> Dict[str, Any]:
        if not rep.get("ok"):
            violations.append(f"{what}: {rep}")
        return rep

    try:
        for node, chips in (("n0", 4), ("n1", 4), ("n2", 2)):
            ok(coord.dispatch({"kind": CL.CL_JOIN, "node": node,
                               "broker": f"/run/vtpu/{node}.sock",
                               "chips": chips, "hbm": 1 << 30,
                               "topology": {"kind": "ring",
                                            "size": chips}}),
               f"join {node}")
        # pack: a(2) lands on the tightest fit (n2), then b/c single
        # chips fill n0, d(4) takes the only node with 4 free (n1).
        for tenant, width, hbm in (("a", 2, 256), ("b", 1, 64),
                                   ("c", 1, 64), ("d", 4, 128)):
            ok(coord.dispatch({"kind": CL.CL_PLACE, "tenant": tenant,
                               "chips": width, "hbm": hbm}),
               f"place {tenant}")
        # Release + re-grant: e must be able to reuse b's freed chip.
        ok(coord.dispatch({"kind": CL.CL_RELEASE, "tenant": "b"}),
           "release b")
        ok(coord.dispatch({"kind": CL.CL_PLACE, "tenant": "e",
                           "chips": 1, "hbm": 32}), "place e")
        # Cross-node migration of the 2-chip grant, journaled exactly
        # as Coordinator._migrate journals it around the broker dance
        # (the dance itself needs live brokers; the LEDGER writes are
        # what this engine checks).
        with coord.mu:
            src = coord.state["placements"]["a"]["node"]
            width = len(coord.state["placements"]["a"]["chips"])
            inv = CL.cluster_inventory(coord.state)
        inv.pop(src, None)
        to, chips, _sb = cluster_choose_placement(inv, width,
                                                  policy="pack")
        if to is None:
            violations.append("canned migration found no target")
        else:
            coord._append({"op": "cmigrate", "tenant": "a",
                           "phase": "begin", "to_node": to,
                           "to_chips": chips})
            coord._append({"op": "cmigrate", "tenant": "a",
                           "phase": "commit", "to_node": to,
                           "to_chips": chips})
        # An aborted migration: begin + abort, ledger must not move.
        coord._append({"op": "cmigrate", "tenant": "e",
                       "phase": "begin", "to_node": "n2",
                       "to_chips": [0]})
        coord._append({"op": "cmigrate", "tenant": "e",
                       "phase": "abort"})
        # Node death: n1 holds the 4-chip grant and no survivor can
        # take it — the re-placement falls back to releasing it.
        coord._node_down("n1")
        ok(coord.dispatch({"kind": CL.CL_RELEASE, "tenant": "c"}),
           "release c")
        violations.extend(CL.check_conservation(coord.state))
    finally:
        coord.stop()
        coord.jr.close()
    return violations


# ---------------------------------------------------------------------------
# Independent interpretation (ground truth)
# ---------------------------------------------------------------------------

def _predict_cluster(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Independent reading of a cluster-record prefix: what a correct
    replay MUST reconstruct.  Deliberately re-implemented from the
    docs/FEDERATION.md record contract, not from
    ``cluster_apply_record`` — a skipped or wrong replay arm shows up
    as a divergence.  The per-node ``used`` ledgers are DERIVED from
    the placements (the conservation identity), never maintained
    incrementally."""
    epoch: Optional[str] = None
    generation: Optional[int] = None
    nodes: Dict[str, Dict[str, Any]] = {}
    placements: Dict[str, Dict[str, Any]] = {}
    migrating: Dict[str, bool] = {}
    placements_total = 0
    migrations_total = 0
    for rec in records:
        op = rec.get("op")
        if op == "cepoch":
            epoch = rec.get("epoch")
            generation = rec.get("generation")
        elif op == "node":
            ent = nodes.setdefault(str(rec["node"]), {})
            ent["chips"] = int(rec.get("chips") or 0)
            ent["broker"] = rec.get("broker")
            ent["alive"] = True
        elif op == "node_down":
            if str(rec.get("node")) in nodes:
                nodes[str(rec["node"])]["alive"] = False
        elif op == "cgrant":
            placements[str(rec["tenant"])] = {
                "node": str(rec["node"]),
                "chips": [int(c) for c in rec.get("chips") or []],
                "hbm": rec.get("hbm")}
            placements_total += 1
        elif op == "crelease":
            placements.pop(str(rec.get("tenant")), None)
        elif op == "cmigrate":
            tenant = str(rec.get("tenant"))
            phase = rec.get("phase")
            if phase == "begin":
                migrating[tenant] = True
            elif phase == "commit":
                old = placements.get(tenant) or {}
                placements[tenant] = {
                    "node": str(rec["to_node"]),
                    "chips": [int(c) for c in rec.get("to_chips")
                              or []],
                    "hbm": old.get("hbm") if rec.get("hbm") is None
                    else rec.get("hbm")}
                migrating.pop(tenant, None)
                migrations_total += 1
            elif phase == "abort":
                migrating.pop(tenant, None)
    used: Dict[str, Dict[str, str]] = {}
    for tenant, p in placements.items():
        per = used.setdefault(p["node"], {})
        for c in p["chips"]:
            per[str(c)] = tenant
    return {"epoch": epoch, "generation": generation,
            "nodes": nodes, "placements": placements,
            "used": used, "migrating": sorted(migrating),
            "placements_total": placements_total,
            "migrations_total": migrations_total}


def cluster_digest(state: Dict[str, Any]) -> Dict[str, Any]:
    """A replayed (or predicted) cluster state rendered into one
    comparable shape.  Empty per-node ledgers are dropped: replay
    keeps a node's empty dict around after its last release, the
    independent reading never creates one — both mean 'nothing
    granted'."""
    return {
        "epoch": state.get("epoch"),
        "generation": state.get("generation"),
        "nodes": {n: {"chips": int(e.get("chips") or 0),
                      "broker": e.get("broker"),
                      "alive": bool(e.get("alive"))}
                  for n, e in (state.get("nodes") or {}).items()},
        "placements": {t: {"node": p.get("node"),
                           "chips": [int(c) for c in p.get("chips")
                                     or []],
                           "hbm": p.get("hbm")}
                       for t, p in (state.get("placements")
                                    or {}).items()},
        "used": {n: dict(sorted(per.items()))
                 for n, per in (state.get("used") or {}).items()
                 if per},
        "migrating": sorted(state.get("migrating") or {}),
        "placements_total": int(state.get("placements_total", 0)),
        "migrations_total": int(state.get("migrations_total", 0)),
    }


# ---------------------------------------------------------------------------
# Exploration
# ---------------------------------------------------------------------------

def _load_cut(cut_dir: str) -> Dict[str, Any]:
    """One recovery of a cut prefix through the REAL machinery: a
    fresh Journal wired to the cluster replay arm, exactly how a
    restarted coordinator boots."""
    from ...runtime import cluster as CL
    from ...runtime.journal import Journal
    jr = Journal(cut_dir, fsync=False, snapshot_every=100_000,
                 apply_fn=CL.cluster_apply_record)
    try:
        return jr.load_state() or {}
    finally:
        jr.close()


def _migrate_checks(ctx: ClusterCutContext,
                    prefix: List[Dict[str, Any]],
                    state: Dict[str, Any]) -> None:
    """migrate-conserves-ledger-cross-node: for every tenant whose
    LAST ledger-affecting record in the prefix is a cmigrate COMMIT,
    the replayed placement must sit exactly on the journaled target —
    same node, same chips — with the target node's ledger holding
    precisely those chips and NO other node holding any (source
    release happened, nothing was lost or duplicated in the move)."""
    last: Dict[str, Any] = {}
    for rec in prefix:
        op = rec.get("op")
        if op == "cgrant":
            last[str(rec["tenant"])] = ("grant", rec)
        elif op == "crelease":
            last[str(rec.get("tenant"))] = ("release", rec)
        elif op == "cmigrate" and rec.get("phase") == "commit":
            last[str(rec["tenant"])] = ("commit", rec)
    placements = state.get("placements") or {}
    used = state.get("used") or {}
    for tenant, (kind, rec) in sorted(last.items()):
        if kind != "commit":
            continue
        to_node = str(rec.get("to_node"))
        want = sorted(int(c) for c in rec.get("to_chips") or [])
        p = placements.get(tenant)
        if p is None:
            ctx.cmigrate_violations.append(
                f"cut {ctx.label}: migrated tenant {tenant!r} has no "
                f"placement after the journaled commit (the grant was "
                f"lost in the move)")
            continue
        got = sorted(int(c) for c in p.get("chips") or [])
        if p.get("node") != to_node or got != want:
            ctx.cmigrate_violations.append(
                f"cut {ctx.label}: migrated tenant {tenant!r} "
                f"recovered on {p.get('node')}/{got} instead of the "
                f"journaled target {to_node}/{want}")
        held = sorted(int(k) for k, v in (used.get(to_node)
                                          or {}).items()
                      if v == tenant)
        if held != want:
            ctx.cmigrate_violations.append(
                f"cut {ctx.label}: target node {to_node!r} ledger "
                f"holds chips {held} for migrated tenant {tenant!r} "
                f"instead of {want} (the move lost or duplicated "
                f"chips)")
        for node, per in sorted(used.items()):
            if node == to_node:
                continue
            stray = sorted(k for k, v in per.items() if v == tenant)
            if stray:
                ctx.cmigrate_violations.append(
                    f"cut {ctx.label}: node {node!r} still holds "
                    f"chips {stray} for migrated tenant {tenant!r} "
                    f"after the commit (source was never released — "
                    f"the chip is double-granted across the "
                    f"migration)")


def explore(record_dir: Optional[str] = None,
            workdir: Optional[str] = None) -> ClusterStats:
    """The full cluster-ledger crash-cut exploration.  ``record_dir``:
    reuse an existing recording (tests; seeded-violation runs record
    PRISTINE first, then patch only the replay)."""
    from ...runtime import cluster as CL
    from ...runtime import replication as repl
    from ...runtime.journal import LOG_NAME, Journal, JournalCorrupt

    stats = ClusterStats()
    tmp = workdir or tempfile.mkdtemp(prefix="vtpu-mc-cluster-")
    own_tmp = workdir is None
    try:
        jdir = record_dir or os.path.join(tmp, "recording")
        if record_dir is None:
            os.makedirs(jdir, exist_ok=True)
            rec_violations = record_cluster_session(jdir)
            if rec_violations:
                stats.violations.extend(
                    f"[recording] {v}" for v in rec_violations)
                return stats
        with open(os.path.join(jdir, LOG_NAME), "rb") as f:
            log = f.read()
        records = split_records(log)
        stats.records = len(records)
        boundaries = [0] + [end for _s, end, _r in records]

        def _labels(i: int) -> str:
            if i == 0:
                return "cboundary[0]=<empty>"
            _s, _e, r = records[i - 1]
            what = r.get("tenant") or r.get("node") or ""
            op = r.get("op")
            if op == "cmigrate":
                op = f"cmigrate-{r.get('phase')}"
            return f"cboundary[{i}]=after-{op}:{what}"

        def _write_cut(name: str, data: bytes) -> str:
            cut = os.path.join(tmp, name)
            os.makedirs(cut, exist_ok=True)
            with open(os.path.join(cut, LOG_NAME), "wb") as f:
                f.write(data)
            return cut

        # -- every record boundary ------------------------------------
        for i, off in enumerate(boundaries):
            label = _labels(i)
            cut = _write_cut(f"cut{i}", log[:off])
            ctx = ClusterCutContext(label=label, state_a={},
                                    state_b={})
            raw_a = _load_cut(cut)
            raw_b = _load_cut(cut)
            ctx.state_a = cluster_digest(raw_a)
            ctx.state_b = cluster_digest(raw_b)
            if ctx.state_a != ctx.state_b:
                ctx.cluster_violations.append(
                    f"cut {label}: two replays of the same ledger "
                    f"prefix disagree (replay is nondeterministic)")
            prefix = [r for _s, _e, r in records[:i]]
            ctx.expected = cluster_digest(_predict_cluster(prefix))
            if ctx.state_a != ctx.expected:
                ctx.cluster_violations.append(
                    f"cut {label}: replayed cluster ledger diverges "
                    f"from the independent reading: got "
                    f"{ctx.state_a!r}, expected {ctx.expected!r}")
            for v in CL.check_conservation(raw_a):
                ctx.cluster_violations.append(f"cut {label}: {v}")
            _migrate_checks(ctx, prefix, raw_a)
            stats.violations.extend(
                inv_registry.run_checks("cluster", "cut", ctx))
            stats.boundary_cuts += 1
            shutil.rmtree(cut, ignore_errors=True)

        # -- torn tails: a cut MID-record must land exactly on the
        # previous boundary (judged independently) --------------------
        for i, (start, end, r) in enumerate(records):
            frag = start + max((end - start) // 2, 1)
            label = f"ctorn[{i}]=mid-{r.get('op')}"
            cut = _write_cut(f"torn{i}", log[:frag])
            ctx = ClusterCutContext(label=label, state_a={},
                                    state_b={})
            try:
                ctx.state_a = ctx.state_b = cluster_digest(
                    _load_cut(cut))
                want = cluster_digest(_predict_cluster(
                    [x for _s, _e, x in records[:i]]))
                if ctx.state_a != want:
                    ctx.cluster_violations.append(
                        f"cut {label}: torn tail was not dropped "
                        f"cleanly — recovered ledger differs from the "
                        f"last complete boundary[{i}]")
            except JournalCorrupt as e:
                ctx.cluster_violations.append(
                    f"cut {label}: torn FINAL record must be dropped, "
                    f"not treated as corruption ({e})")
            stats.violations.extend(
                inv_registry.run_checks("cluster", "cut", ctx))
            stats.torn_cuts += 1
            shutil.rmtree(cut, ignore_errors=True)

        # -- non-tail damage must fail closed -------------------------
        for case, mutate in (
            ("flip-mid-log", lambda b: _flip_byte(b, records)),
            ("truncate-first-line", lambda b: b[3:]),
        ):
            cut = _write_cut(f"corrupt-{case}", mutate(log))
            ctx = ClusterCutContext(label=f"ccorrupt[{case}]",
                                    state_a={}, state_b={})
            try:
                _load_cut(cut)
                ctx.cluster_violations.append(
                    f"ccorrupt[{case}]: recovery proceeded on "
                    f"non-tail ledger damage instead of raising "
                    f"JournalCorrupt")
            except JournalCorrupt:
                pass
            stats.violations.extend(
                inv_registry.run_checks("cluster", "cut", ctx))
            stats.corrupt_checks += 1
            shutil.rmtree(cut, ignore_errors=True)

        # -- epoch fencing: a superseded coordinator can never journal
        # (and so never ack) a ledger change — the exact Coordinator
        # wiring: Fence.claim at boot, jr.fence = fence.check ----------
        ctx = ClusterCutContext(label="cfence[takeover]", state_a={},
                                state_b={})
        fdir = os.path.join(tmp, "cfence")
        os.makedirs(fdir, exist_ok=True)
        fpath = os.path.join(fdir, "cl.sock.fence")
        stale = repl.Fence(fpath, enabled=True)
        stale.claim("c-old-epoch")
        taker = repl.Fence(fpath, enabled=True)
        taker.claim("c-new-epoch")
        fired = False
        try:
            stale.check()
        except OSError:
            fired = True
        if not fired:
            ctx.cfence_violations.append(
                "a stale coordinator's fence check passed after a "
                "successor claimed a newer generation")
        fenced_jr = Journal(os.path.join(fdir, "j"),
                            snapshot_every=100_000, fsync=False,
                            apply_fn=CL.cluster_apply_record)
        fenced_jr.fence = stale.check
        try:
            fenced_jr.append({"op": "cgrant", "tenant": "ghost",
                              "node": "n0", "chips": [0]})
            ctx.cfence_violations.append(
                "a ledger journal wired to a fenced coordinator epoch "
                "still accepted a cgrant append (a stale coordinator "
                "could place — and ack — after its successor took "
                "over)")
        except OSError:
            pass
        fenced_jr.close()
        try:
            taker.check()
        except OSError:
            ctx.cfence_violations.append(
                "the succeeding coordinator's own fence check refused "
                "its freshly claimed generation")
        stats.violations.extend(
            inv_registry.run_checks("cluster", "cut", ctx))
        stats.fence_checks += 1
    finally:
        if own_tmp:
            shutil.rmtree(tmp, ignore_errors=True)
    return stats
