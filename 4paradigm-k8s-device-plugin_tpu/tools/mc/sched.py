"""Cooperative deterministic scheduler — the substrate vtpu-mc runs the
REAL broker code on.

The broker's concurrency surface (``runtime/server.py`` scheduling,
lease grant/burn/refund, ``runtime/journal.py`` deferred appends) is
written against three stdlib primitives: ``threading`` (Lock /
Condition / Thread), ``queue.Queue`` and ``time``.  This module
provides drop-in shims for all three whose every visible operation is a
YIELD POINT: the operation is announced to a controller, the task
parks, and the controller decides which parked task runs next.  Exactly
one task runs at a time, so a run is fully determined by the sequence
of controller decisions — the schedule — and the same decision sequence
replays the same execution (loom/shuttle-style schedule control;
FoundationDB-style determinism).

The shims are injected by rebinding the MODULE-LEVEL names the broker
modules imported (``vtpu.runtime.server.threading = <shim>`` etc.), so
only the code under test is redirected — the controller itself, pytest,
and any real broker in the same process keep the real primitives.

Time is a logical clock: it only advances when the controller decides
no task is runnable and jumps straight to the earliest deadline among
timed waiters (discrete-event style), so lease TTL expiry, dispatcher
idle sleeps and quiesce polls are all explorable schedule events
instead of wall-clock behavior.

Lost-wake oracle: the dispatcher's IDLE sleep (the 0.5 s default
timeout it uses only when ``_pick_locked`` reported no time-gated
work) ending by TIMEOUT while its scheduler holds dispatchable work is
exactly a lost wake — a correct broker's submit/retire/kick paths
would have notified it.  The controller reports every timeout wake to
the harness (``on_timeout_wake``) which applies that judgment.
"""

from __future__ import annotations

import queue as real_queue
import threading as real_threading
from typing import Any, Callable, Dict, List, Optional, Tuple

# Decision-step ceiling per schedule: a scenario exceeding it is a
# livelock (or a runaway daemon) — surfaced as a violation, never an
# endless run.
DEFAULT_MAX_STEPS = 20000
# Clock-advance ceiling per schedule (each advance jumps to the next
# deadline; a correct scenario needs only a handful).
DEFAULT_MAX_ADVANCES = 400


class MCAbort(BaseException):
    """Raised inside a task thread to unwind it when the controller
    abandons a schedule.  BaseException on purpose: the broker's
    ``except Exception`` arms must not swallow it."""


class ReplayDivergence(RuntimeError):
    """A scripted replay saw a different enabled set than the recording
    run — the scenario is nondeterministic (harness bug)."""


class DeadlockError(RuntimeError):
    pass


class MCClock:
    """Logical monotonic+wall clock (ns)."""

    def __init__(self) -> None:
        self.ns = 1_000_000_000  # 1s, so timestamps are never 0/False

    def now(self) -> float:
        return self.ns / 1e9

    # -- the `time` module surface the broker uses --
    def monotonic(self) -> float:
        return self.ns / 1e9

    def time(self) -> float:
        return self.ns / 1e9

    def time_ns(self) -> int:
        return self.ns

    def sleep(self, s: float) -> None:  # pragma: no cover - unused path
        self.ns += int(s * 1e9)

    def advance_to(self, t: float) -> None:
        self.ns = max(self.ns, int(t * 1e9))


class MCTask:
    """One logical thread of the scenario, backed by a real OS thread
    that is parked on a semaphore except while the controller grants it
    a slice."""

    def __init__(self, sched: "Scheduler", tid: int, name: str,
                 fn: Callable[[], Any], daemon: bool) -> None:
        self.sched = sched
        self.tid = tid
        self.name = name
        self.fn = fn
        self.daemon = daemon
        self.sem = real_threading.Semaphore(0)
        self.state = "new"      # new|runnable|blocked|waiting|done
        self.pending: Optional[Tuple] = None  # announced next op
        self.wait_obj: Optional[Any] = None   # cond/queue parked on
        self.deadline: Optional[float] = None
        self.woke_by_timeout = False
        self.wait_timeout: Optional[float] = None
        self.error: Optional[BaseException] = None
        self.thread = real_threading.Thread(
            target=self._run, name=f"mc-{name}", daemon=True)

    def _run(self) -> None:
        self.sem.acquire()
        if self.sched.aborting:
            self.state = "done"
            self.sched._ctrl.release()
            return
        try:
            self.fn()
        except MCAbort:
            pass
        except BaseException as e:  # noqa: BLE001 - surfaced as violation
            self.error = e
        self.state = "done"
        self.sched._ctrl.release()

    def start(self) -> None:
        self.state = "runnable"
        self.pending = ("start", None)
        self.thread.start()


class Scheduler:
    """The controller: owns the task set, the logical clock, and the
    decision loop.  ``choose(step, enabled)`` — supplied by the
    explorer — picks which enabled task runs the next slice."""

    def __init__(self, clock: Optional[MCClock] = None,
                 max_steps: int = DEFAULT_MAX_STEPS,
                 max_advances: int = DEFAULT_MAX_ADVANCES) -> None:
        self.clock = clock or MCClock()
        self.tasks: List[MCTask] = []
        self._ctrl = real_threading.Semaphore(0)
        self._current: Optional[MCTask] = None
        self.aborting = False
        self.max_steps = max_steps
        self.max_advances = max_advances
        self.steps = 0
        self.advances = 0
        self.violations: List[str] = []
        # Hooks the harness installs.
        self.on_timeout_wake: Optional[Callable[[MCTask, Any, float],
                                               None]] = None
        self.quiescent: Optional[Callable[[], bool]] = None
        self.on_quiescent: Optional[Callable[[], None]] = None
        self.step_check: Optional[Callable[[], List[str]]] = None

    # -- task-side API (runs on task threads) -----------------------------

    def current(self) -> MCTask:
        t = self._current
        assert t is not None, "MC primitive used outside a task slice"
        return t

    def _park(self, task: MCTask) -> None:
        """Hand control back and wait to be granted the announced op."""
        self._ctrl.release()
        task.sem.acquire()
        if self.aborting:
            raise MCAbort()

    def announce(self, op: Tuple) -> None:
        """Yield point: announce the op the task is ABOUT to perform
        (it executes at the top of the task's next slice)."""
        task = self.current()
        task.pending = op
        task.state = "runnable"
        self._park(task)

    def block_on(self, op: Tuple, obj: Any,
                 deadline: Optional[float],
                 timeout: Optional[float] = None) -> bool:
        """Park as waiting on ``obj`` (condition or queue) until woken
        by a notifier or — when ``deadline`` is set — by a clock
        advance.  Returns True when the wake was a timeout."""
        task = self.current()
        task.pending = op
        task.state = "waiting"
        task.wait_obj = obj
        task.deadline = deadline
        task.wait_timeout = timeout
        task.woke_by_timeout = False
        self._park(task)
        task.wait_obj = None
        task.deadline = None
        return task.woke_by_timeout

    # -- controller-side --------------------------------------------------

    def spawn(self, fn: Callable[[], Any], name: str,
              daemon: bool = False) -> MCTask:
        task = MCTask(self, len(self.tasks), name, fn, daemon)
        self.tasks.append(task)
        task.start()
        return task

    def _enabled(self) -> List[MCTask]:
        out = []
        for t in self.tasks:
            if t.state != "runnable":
                continue
            op = t.pending or ("start", None)
            if op[0] == "acq" and op[1].owner is not None:
                continue
            if op[0] == "qget" and not op[1].items:
                # announced get on an empty queue: converts to waiting
                # (handled in MCQueue.get) — treat as not enabled here
                continue
            out.append(t)
        return out

    def _wake(self, task: MCTask, timeout: bool) -> None:
        task.woke_by_timeout = timeout
        task.state = "runnable"

    def _advance_clock(self) -> bool:
        """Jump to the earliest deadline among timed waiters and wake
        them.  Returns False when nobody is waiting on time."""
        waiters = [t for t in self.tasks
                   if t.state == "waiting" and t.deadline is not None]
        if not waiters:
            return False
        self.advances += 1
        if self.advances > self.max_advances:
            self.violations.append(
                "livelock: clock advanced %d times without reaching a "
                "terminal state" % self.advances)
            return False
        dl = min(t.deadline for t in waiters)
        self.clock.advance_to(dl)
        for t in waiters:
            if t.deadline is not None and t.deadline <= dl + 1e-12:
                if self.on_timeout_wake is not None:
                    self.on_timeout_wake(t, t.wait_obj,
                                         t.wait_timeout or 0.0)
                self._wake(t, timeout=True)
        return True

    def _step(self, task: MCTask) -> None:
        self.steps += 1
        self._current = task
        task.sem.release()
        self._ctrl.acquire()
        self._current = None

    def run(self, choose: Callable[[int, List[MCTask]], MCTask]
            ) -> None:
        """Drive the schedule to a terminal state: all non-daemon tasks
        done and the harness-declared quiescence reached; then stop the
        daemons cleanly.  Violations (deadlock, livelock, task crash,
        step-hook findings) accumulate in ``self.violations``."""
        step = 0
        while True:
            if self.steps > self.max_steps:
                self.violations.append(
                    "livelock: schedule exceeded %d decision steps"
                    % self.max_steps)
                break
            if self.step_check is not None:
                v = self.step_check()
                if v:
                    self.violations.extend(v)
                    break
            enabled = self._enabled()
            if enabled:
                task = choose(step, enabled)
                step += 1
                self._step(task)
                continue
            # Nothing runnable: terminal, clock advance, or deadlock.
            live = [t for t in self.tasks
                    if not t.daemon and t.state != "done"]
            if not live and (self.quiescent is None or self.quiescent()):
                break
            if self._advance_clock():
                if self.advances > self.max_advances:
                    break
                continue
            self.violations.append(
                "deadlock: tasks stuck with no timed waiter: "
                + ", ".join(f"{t.name}({t.state} on {t.pending})"
                            for t in self.tasks if t.state != "done"))
            break
        if self.on_quiescent is not None and not self.violations:
            self.on_quiescent()
        for t in self.tasks:
            if t.error is not None:
                self.violations.append(
                    f"task {t.name} crashed: "
                    f"{type(t.error).__name__}: {t.error}")
        self._shutdown()

    def _shutdown(self) -> None:
        """Unwind every unfinished task thread (abort at its next yield
        point) so schedules never leak OS threads."""
        self.aborting = True
        for _ in range(len(self.tasks) * 4 + 16):
            live = [t for t in self.tasks if t.state != "done"]
            if not live:
                break
            self._step(live[0])
        for t in self.tasks:
            t.thread.join(timeout=5.0)


# ---------------------------------------------------------------------------
# The primitive shims the broker modules are rebound to.
# ---------------------------------------------------------------------------

class MCLock:
    """Cooperative lock: acquisition is a yield point; ownership is a
    plain field only the single running task mutates."""

    _ids = 0

    def __init__(self, sched: Scheduler, name: str = "") -> None:
        MCLock._ids += 1
        self.sched = sched
        self.lid = MCLock._ids
        self.name = name or f"lock{self.lid}"
        self.owner: Optional[MCTask] = None

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        if self.sched.aborting:
            # Post-run controller-side use (journal.close during
            # schedule teardown): no parking, no ownership games.
            return True
        self.sched.announce(("acq", self))
        me = self.sched.current()
        assert self.owner is None, \
            f"MC granted held lock {self.name} to {me.name}"
        self.owner = me
        return True

    def release(self) -> None:
        if self.sched.aborting:
            # MCAbort unwind: `with` __exit__ paths release whatever
            # the task held; no assertions, no parking.
            self.owner = None
            return
        assert self.owner is self.sched.current()
        self.owner = None
        self.sched.announce(("rel", self))

    def __enter__(self) -> "MCLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def locked(self) -> bool:
        return self.owner is not None


class MCCondition:
    """Cooperative condition variable over an MCLock."""

    def __init__(self, sched: Scheduler,
                 lock: Optional[MCLock] = None) -> None:
        self.sched = sched
        self.lock = lock or MCLock(sched)
        self.waiters: List[MCTask] = []

    # Lock surface (``with cond:`` / cond.acquire()).
    def acquire(self, *a: Any, **kw: Any) -> bool:
        return self.lock.acquire()

    def release(self) -> None:
        self.lock.release()

    def __enter__(self) -> "MCCondition":
        self.lock.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.lock.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        me = self.sched.current()
        assert self.lock.owner is me, "wait() without holding the lock"
        self.lock.owner = None  # atomically release with the park
        self.waiters.append(me)
        deadline = (self.sched.clock.now() + timeout
                    if timeout is not None else None)
        timed_out = self.sched.block_on(("cwait", self), self, deadline,
                                        timeout)
        if me in self.waiters:
            self.waiters.remove(me)
        # Re-acquire before returning, like the real primitive.
        self.sched.announce(("acq", self.lock))
        assert self.lock.owner is None
        self.lock.owner = me
        return not timed_out

    def _notify(self, n: Optional[int]) -> None:
        woken = self.waiters if n is None else self.waiters[:n]
        for t in list(woken):
            self.waiters.remove(t)
            self.sched._wake(t, timeout=False)

    def notify(self, n: int = 1) -> None:
        me = self.sched.current()
        assert self.lock.owner is me, "notify() without holding the lock"
        self._notify(n)

    def notify_all(self) -> None:
        me = self.sched.current()
        assert self.lock.owner is me, \
            "notify_all() without holding the lock"
        self._notify(None)


class MCEvent:
    """Cooperative Event (broker uses it only for keeper shutdown)."""

    def __init__(self, sched: Scheduler) -> None:
        self.sched = sched
        self._set = False

    def set(self) -> None:
        self._set = True

    def is_set(self) -> bool:
        return self._set

    def wait(self, timeout: Optional[float] = None) -> bool:
        if self._set:
            return True
        deadline = (self.sched.clock.now() + timeout
                    if timeout is not None else None)
        self.sched.block_on(("ewait", self), self, deadline, timeout)
        return self._set


class MCQueue:
    """Cooperative queue.Queue subset (put / get / get_nowait)."""

    def __init__(self, sched: Scheduler, maxsize: int = 0) -> None:
        self.sched = sched
        self.items: List[Any] = []
        self.waiters: List[MCTask] = []

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        self.sched.announce(("qput", self))
        self.items.append(item)
        for t in list(self.waiters):
            self.waiters.remove(t)
            self.sched._wake(t, timeout=False)

    def get(self, block: bool = True,
            timeout: Optional[float] = None) -> Any:
        if not block:
            return self.get_nowait()
        me = self.sched.current()
        while True:
            if self.items:
                self.sched.announce(("qget", self))
                # Another task may have raced the announce; re-check.
                if self.items:
                    return self.items.pop(0)
                continue
            self.waiters.append(me)
            deadline = (self.sched.clock.now() + timeout
                        if timeout is not None else None)
            timed_out = self.sched.block_on(("qwait", self), self,
                                            deadline, timeout)
            if me in self.waiters:
                self.waiters.remove(me)
            if self.items:
                return self.items.pop(0)
            if timed_out:
                raise real_queue.Empty()

    def get_nowait(self) -> Any:
        # Distinct op tag: a non-blocking get on an EMPTY queue must
        # still be schedulable (it proceeds by raising Empty — the
        # completion loop's drain-cap probe depends on it), while a
        # blocking get's announce is only enabled when items exist.
        self.sched.announce(("qget_nb", self))
        if not self.items:
            raise real_queue.Empty()
        return self.items.pop(0)

    def qsize(self) -> int:
        return len(self.items)

    def empty(self) -> bool:
        return not self.items


class MCThread:
    """threading.Thread stand-in: ``start`` registers the target as an
    MC DAEMON task (the broker only spawns daemon service loops —
    dispatcher, completer, keepers)."""

    def __init__(self, sched: Scheduler, target: Callable[..., Any],
                 args: Tuple = (), daemon: bool = True,
                 name: str = "thread") -> None:
        self.sched = sched
        self.target = target
        self.args = args
        self.name = name
        self.daemon = daemon
        self.task: Optional[MCTask] = None

    def start(self) -> None:
        self.task = self.sched.spawn(
            lambda: self.target(*self.args), self.name, daemon=True)

    def join(self, timeout: Optional[float] = None) -> None:
        pass  # controller owns lifecycle


class _ShimModule:
    """Attribute bag standing in for a stdlib module inside the broker
    modules' namespaces."""

    def __init__(self, **attrs: Any) -> None:
        self.__dict__.update(attrs)


def make_shims(sched: Scheduler) -> Dict[str, Any]:
    """The three module shims, bound to one scheduler."""
    def Lock() -> MCLock:
        return MCLock(sched)

    def Condition(lock: Optional[MCLock] = None) -> MCCondition:
        return MCCondition(sched, lock)

    def Event() -> MCEvent:
        return MCEvent(sched)

    def Thread(target: Callable[..., Any] = None, args: Tuple = (),
               daemon: bool = True, name: str = "thread") -> MCThread:
        return MCThread(sched, target, args, daemon, name)

    def Queue(maxsize: int = 0) -> MCQueue:
        return MCQueue(sched, maxsize)

    threading_shim = _ShimModule(
        Lock=Lock, RLock=Lock, Condition=Condition, Event=Event,
        Thread=Thread, get_ident=real_threading.get_ident,
        current_thread=real_threading.current_thread)
    queue_shim = _ShimModule(Queue=Queue, Empty=real_queue.Empty,
                             Full=real_queue.Full)
    time_shim = _ShimModule(
        monotonic=sched.clock.monotonic, time=sched.clock.time,
        time_ns=sched.clock.time_ns, sleep=sched.clock.sleep,
        perf_counter=sched.clock.monotonic)
    return {"threading": threading_shim, "queue": queue_shim,
            "time": time_shim}


# ---------------------------------------------------------------------------
# Inert shims: single-threaded stand-ins for the crash-cut engine.
#
# Journal recovery (``RuntimeState._recover_from_journal`` + resume) is
# sequential code — it needs no schedule exploration, but building the
# broker stub must not spawn real dispatcher/completer threads per cut
# (hundreds of cuts would leak hundreds of parked OS threads).  These
# shims make every lock a no-op, every Thread.start a no-op, and time a
# plain logical clock.
# ---------------------------------------------------------------------------

class InertLock:
    def __init__(self, *a: Any, **kw: Any) -> None:
        self._depth = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._depth += 1
        return True

    def release(self) -> None:
        self._depth -= 1

    def __enter__(self) -> "InertLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def locked(self) -> bool:
        return self._depth > 0


class InertCondition(InertLock):
    def __init__(self, lock: Optional[InertLock] = None,
                 clock: Optional[MCClock] = None) -> None:
        super().__init__()
        self._clock = clock

    def wait(self, timeout: Optional[float] = None) -> bool:
        # Nothing can notify a single-threaded waiter: advance the
        # clock so deadline'd loops (quiesce) terminate.
        if self._clock is not None and timeout is not None:
            self._clock.advance_to(self._clock.now() + timeout)
        return False

    def notify(self, n: int = 1) -> None:
        pass

    def notify_all(self) -> None:
        pass


class InertEvent:
    def __init__(self) -> None:
        self._set = False

    def set(self) -> None:
        self._set = True

    def is_set(self) -> bool:
        return self._set

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._set


class InertThread:
    """Thread whose start() is a no-op: service loops simply never run
    (recovery touches none of them)."""

    def __init__(self, target: Callable[..., Any] = None, args: Tuple = (),
                 daemon: bool = True, name: str = "thread") -> None:
        self.name = name

    def start(self) -> None:
        pass

    def join(self, timeout: Optional[float] = None) -> None:
        pass


class InertScheduler:
    """Duck-typed stand-in for ``Scheduler`` that the crash-cut harness
    passes to ``Harness``: carries the logical clock and accepts (and
    ignores) the oracle hooks the harness installs."""

    def __init__(self, clock: Optional[MCClock] = None) -> None:
        self.clock = clock or MCClock()
        self.on_timeout_wake: Optional[Callable] = None
        self.quiescent: Optional[Callable[[], bool]] = None
        self.step_check: Optional[Callable[[], List[str]]] = None
        self.on_quiescent: Optional[Callable[[], None]] = None
        self.aborting = False

    def block_on(self, *a: Any, **kw: Any) -> bool:  # MCEvent compat
        return False


def make_inert_shims(clock: MCClock) -> Dict[str, Any]:
    def Condition(lock: Optional[InertLock] = None) -> InertCondition:
        return InertCondition(lock, clock=clock)

    threading_shim = _ShimModule(
        Lock=InertLock, RLock=InertLock, Condition=Condition,
        Event=InertEvent, Thread=InertThread,
        get_ident=real_threading.get_ident,
        current_thread=real_threading.current_thread)
    queue_shim = _ShimModule(Queue=real_queue.Queue,
                             Empty=real_queue.Empty, Full=real_queue.Full)
    time_shim = _ShimModule(
        monotonic=clock.monotonic, time=clock.time, time_ns=clock.time_ns,
        sleep=clock.sleep, perf_counter=clock.monotonic)
    return {"threading": threading_shim, "queue": queue_shim,
            "time": time_shim}


class patched_modules:
    """Context manager rebinding the stdlib names inside the broker
    modules to this scheduler's shims (and restoring them on exit).

    Only name BINDINGS in the listed modules change — the real stdlib
    modules are untouched, so the controller, pytest and any live
    broker in the same process keep real primitives."""

    # module object -> names to rebind
    TARGETS = {
        "vtpu.runtime.server": ("threading", "time", "queue"),
        "vtpu.runtime.journal": ("threading", "time"),
        # vtpu-fastlane: the drain path stamps/mints off its module
        # clock — real wall time here would branch the explored code
        # paths nondeterministically across replays (mint thresholds,
        # SLO dts) and trip the determinism oracle under load.
        "vtpu.runtime.fastlane": ("threading", "time"),
    }

    def __init__(self, sched: "Scheduler | InertScheduler") -> None:
        if isinstance(sched, InertScheduler):
            self.shims = make_inert_shims(sched.clock)
        else:
            self.shims = make_shims(sched)
        self.saved: List[Tuple[Any, str, Any]] = []

    def __enter__(self) -> "patched_modules":
        import importlib
        for modname, names in self.TARGETS.items():
            mod = importlib.import_module(modname)
            for name in names:
                self.saved.append((mod, name, getattr(mod, name)))
                setattr(mod, name, self.shims[name])
        return self

    def __exit__(self, *exc: Any) -> None:
        for mod, name, val in reversed(self.saved):
            setattr(mod, name, val)
        self.saved.clear()
