"""vtpu-wmm command line — litmus suite, budgets, floor gate,
selfcheck.

Exploration is fully deterministic (DFS over scheduling/visibility
decisions; no randomness anywhere), so CI needs no seed pinning: the
same tree + the same budget flags explore the same executions.  The
CI ``wmm`` job prints the explored-execution counts and floor-gates
them (``--min-executions``): a refactor that silently shrinks the
explored space — a litmus that stopped branching, a budget knob
regression — fails loudly instead of shipping a weaker checker.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

from . import litmus as lt
from . import model, selfcheck


def _run_suite(ns: argparse.Namespace) -> Dict[str, Any]:
    wanted = [lt.get(ns.litmus)] if ns.litmus else list(lt.LITMUS)
    out: Dict[str, Any] = {"litmus": {}, "executions": 0,
                           "decisions": 0, "violations": []}
    for item in wanted:
        stats = model.explore_litmus(
            item, max_executions=ns.max_executions,
            preemption_bound=ns.preemptions)
        out["litmus"][item.name] = {
            "protocol": item.protocol,
            "executions": stats.executions,
            "decisions": stats.decisions,
            "truncated": stats.truncated,
            "violations": stats.violations,
            "witness": stats.witness,
        }
        out["executions"] += stats.executions
        out["decisions"] += stats.decisions
        out["violations"].extend(
            f"{item.name}: {v}" for v in stats.violations)
    return out


def _run_selfcheck(ns: argparse.Namespace) -> int:
    results = selfcheck.run_all(max_executions=ns.max_executions)
    missed = [s.name for s, caught, _n in results if not caught]
    for seed, caught, n in results:
        mark = "caught" if caught else "MISSED"
        print(f"  seed {seed.name:30s} -> {seed.invariant:22s} "
              f"{mark} ({n} violation(s))")
    if missed:
        print(f"vtpu-wmm selfcheck: {len(missed)} seed(s) NOT caught: "
              f"{missed}")
        return 1
    print(f"vtpu-wmm selfcheck: all {len(results)} seeded weak-memory "
          f"bugs caught")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="vtpu-wmm",
        description="weak-memory-model checking of the shared-region "
                    "lock-free protocols (docs/ANALYSIS.md)")
    ap.add_argument("--litmus", default=None,
                    help="run one litmus program by name")
    ap.add_argument("--list", action="store_true",
                    help="list litmus programs and selfcheck seeds, "
                         "then exit")
    ap.add_argument("--max-executions", type=int, default=None,
                    help="execution budget PER litmus (deterministic "
                         "DFS; default VTPU_WMM_MAX_EXECUTIONS or "
                         f"{model.DEFAULT_MAX_EXECUTIONS})")
    ap.add_argument("--preemptions", type=int, default=None,
                    help="CHESS-style preemption budget per execution "
                         "(default VTPU_WMM_PREEMPTIONS or "
                         f"{model.DEFAULT_PREEMPTION_BOUND}; message-"
                         "visibility choices are never bounded)")
    ap.add_argument("--min-executions", type=int, default=0,
                    help="fail unless the suite explored at least "
                         "this many executions in total (CI floor "
                         "gate)")
    ap.add_argument("--selfcheck", action="store_true",
                    help="run the seeded-violation matrix instead: "
                         "every weakened protocol variant must be "
                         "caught by its invariant row")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny budget: the analyze-job wiring check, "
                         "not the real exploration")
    ap.add_argument("--json", action="store_true")
    ns = ap.parse_args(argv)

    if ns.list:
        print("litmus programs:")
        for item in lt.LITMUS:
            print(f"  {item.name:16s} [{item.protocol:16s}] "
                  f"{item.description}")
        print("selfcheck seeds:")
        for seed in selfcheck.SEEDS:
            print(f"  {seed.name:30s} -> {seed.invariant}")
        return 0

    if ns.smoke and ns.max_executions is None:
        ns.max_executions = 60

    if ns.selfcheck:
        return _run_selfcheck(ns)

    report = _run_suite(ns)
    if ns.json:
        print(json.dumps(report, indent=2))
    else:
        for name, s in report["litmus"].items():
            print(f"  wmm {name:16s} executions={s['executions']:6d} "
                  f"decisions={s['decisions']:8d}"
                  + (f" truncated={s['truncated']}"
                     if s["truncated"] else ""))
        print(f"  wmm TOTAL: {report['executions']} executions, "
              f"{report['decisions']} decisions")
        for v in report["violations"]:
            print(f"VIOLATION: {v}")
        print(f"vtpu-wmm: {len(report['violations'])} violation(s)")

    if ns.min_executions and report["executions"] < ns.min_executions:
        print(f"vtpu-wmm: explored-execution FLOOR MISSED: "
              f"{report['executions']} < --min-executions "
              f"{ns.min_executions} — the explored space silently "
              f"shrank", file=sys.stderr)
        return 1
    return 1 if report["violations"] else 0
