"""vtpu-wmm seeded-violation selfcheck.

A weak-memory simulator that reports "0 violations" is only
trustworthy if a DELIBERATELY weakened protocol makes it scream.  Each
seed below is a litmus variant with one real bug class injected —
release downgraded to relaxed, the seqlock reader's re-check removed,
a non-atomic read-modify-write on shared ledger state, a crash-atomic
field torn across two words, the planned exec ring publishing its
tail relaxed — and the matching invariant row must fire under the
exploration budget.  ``python -m vtpu.tools.wmm --selfcheck`` runs the
matrix (CI does); tests/test_wmm.py drives the seeds individually.

The weakened variants live in the litmus factories' ``broken=``
parameter, never in any checked source: the protocols stay correct,
and a seed that stops firing means the SIMULATOR regressed.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

from . import litmus as lt
from . import model


@dataclass(frozen=True)
class Seed:
    name: str
    litmus: lt.Litmus
    invariant: str   # registry row expected to fire
    bug: str         # one-line description of the injected bug


SEEDS: Tuple[Seed, ...] = (
    Seed("seqlock-release-downgraded",
         lt.make_trace_ring(broken="relaxed-publish"),
         "wmm-no-torn-payload",
         "trace-ring publish all-relaxed (no fences, no release): the "
         "reader accepts a slot whose payload was never made visible"),
    Seed("seqlock-missing-recheck",
         lt.make_trace_ring(broken="missing-recheck"),
         "wmm-no-torn-payload",
         "reader skips the seq re-check after the copy: a wrap "
         "mid-copy hands back a half-old half-new payload"),
    Seed("ledger-nonatomic-rmw",
         lt.make_ledger_cas(broken="plain-rmw"),
         "wmm-data-race",
         "charge path does plain load+store instead of CAS: a data "
         "race, and lost updates break ledger conservation"),
    Seed("ledger-double-free",
         lt.make_ledger_cas(broken="double-free"),
         "wmm-ledger-conserved",
         "release path runs twice: the same bytes are returned to the "
         "ledger twice (atomically — no race, pure conservation "
         "break)"),
    Seed("lease-plain-burn",
         lt.make_rate_lease(broken="plain-burn"),
         "wmm-lease-bounded",
         "lease burn is a plain read-modify-write racing the revoke "
         "swap: burn + refund exceeds the one debited quantum"),
    Seed("credit-uncapped-plain-mint",
         lt.make_credit_bank(broken="plain-mint"),
         "wmm-credit-bounds",
         "mint writes the bank non-atomically and uncapped: credit "
         "minted from nothing / balance past the cap"),
    Seed("crash-atomic-torn-two-word",
         lt.make_degraded_quota(broken="two-word"),
         "wmm-crash-atomic",
         "quota limit split across two words: the degraded client "
         "combines halves of different epochs into a limit nobody "
         "granted"),
    Seed("exec-ring-relaxed-tail",
         lt.make_exec_ring(broken="relaxed-tail"),
         "wmm-ring-fifo",
         "exec ring publishes tail relaxed: the consumer executes a "
         "descriptor whose words were never published"),
    Seed("exec-ring-skipped-headc-gate",
         lt.make_exec_ring(broken="skip-headc-gate"),
         "wmm-ring-fifo",
         "producer skips the headc slot-reuse gate with a crash-torn "
         "credit counter: the wrap overwrites a descriptor the "
         "consumer has not republished"),
    Seed("multi-ring-relaxed-cvec",
         lt.make_multi_ring(broken="relaxed-cvec"),
         "wmm-no-torn-payload",
         "lead publishes its completion-vector slot relaxed: the "
         "multi-chip join can release a completion whose lead-side "
         "output binds are not yet visible"),
)


def run_seed(seed: Seed,
             max_executions: Optional[int] = None,
             preemption_bound: Optional[int] = None
             ) -> Tuple[bool, List[str]]:
    """Explore one weakened litmus; ``caught`` is True when the
    expected invariant row fired."""
    stats = model.explore_litmus(
        seed.litmus, max_executions=max_executions,
        preemption_bound=preemption_bound)
    tag = f"[{seed.invariant}]"
    return any(tag in v for v in stats.violations), stats.violations


def run_all(max_executions: Optional[int] = None
            ) -> List[Tuple[Seed, bool, int]]:
    results: List[Tuple[Seed, bool, int]] = []
    for seed in SEEDS:
        caught, violations = run_seed(seed,
                                      max_executions=max_executions)
        results.append((seed, caught, len(violations)))
    return results
