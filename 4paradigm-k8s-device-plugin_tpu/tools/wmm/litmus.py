"""vtpu-wmm litmus suite: the REAL shared-region protocol shapes.

Each litmus here is a faithful miniature of a protocol the enforcement
stack runs (or — for the exec ring — is specified to run) over the
mmap'd shared region, written at the exact memory orders the
declaration grammar in ``native/vtpucore/vtpu_core.h`` commits to.
The engine explores every scheduling/visibility choice within the
bounds and holds the outcomes to the ``wmm`` rows of the
``tools/mc/invariants.py`` registry.

Every factory takes a ``broken=`` parameter used ONLY by
``selfcheck.py``: a deliberately weakened variant (release downgraded
to relaxed, missing reader re-check, non-atomic ledger access, torn
two-word crash-atomic update) that the matching invariant row must
catch — the proof the simulator can actually see weak-memory bugs.

Protocol sources:

  - ``trace_ring``      — vtpu_trace_emit / vtpu_trace_read
                          (per-slot seqlock, single-writer ring)
  - ``ledger_cas``      — the declared lock-free charge/free shape of
                          the interposer-only data plane (today the
                          ledger runs under the robust mutex; ROADMAP
                          item 2 moves it onto this CAS protocol)
  - ``rate_lease``      — shim/core.py RateLease pre-debit/burn/refund
                          over the bucket
  - ``credit_bank``     — burst-credit mint/spend (docs/SCHEDULING.md)
                          as cross-process atomics
  - ``degraded_quota``  — runtime/degraded.py: quota read with the
                          broker GONE mid-update (crash-atomic fields)
  - ``exec_ring``       — the PLANNED interposer-only shm execute ring
                          (SPSC descriptor ring + credit gate), spec'd
                          in vtpu_core.h ahead of the ROADMAP item 2
                          build so it lands on verified orders
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Tuple

from .model import ACQ, ACQ_REL, PLAIN, REL, RLX, WmmContext


@dataclass(frozen=True)
class Litmus:
    name: str
    description: str
    protocol: str          # declared protocol this models
    init: Dict[str, int]
    threads: Tuple[Callable, ...]   # each: (out) -> generator
    check: Callable[[WmmContext, Dict[str, Any], Dict[str, int]], None]
    rows: Tuple[str, ...]  # invariant rows it exercises


# ---------------------------------------------------------------------------
# 1. trace-ring seqlock (vtpu_trace_emit / vtpu_trace_read)
# ---------------------------------------------------------------------------

def make_trace_ring(broken: str = "") -> Litmus:
    """2-slot ring, 3 events (one wrap), 2-word payload.  The writer
    follows the vtpu_core.cc publish shape exactly: claim the index
    with an acq_rel fetch_add on head, invalidate (seq=0 relaxed),
    release fence, relaxed payload, release fence, publish seq=idx+1
    release.  The reader: head acquire, seq acquire, relaxed copy,
    acquire fence, seq re-check.  Both release fences and the re-check
    are load-bearing — the broken variants drop them."""
    events = 3

    def writer(out: Dict[str, Any]):
        for _ in range(events):
            idx = yield ("rmw", "head", 1, ACQ_REL)
            s = idx % 2
            val = 100 + idx
            if broken == "relaxed-publish":
                yield ("store", f"seq{s}", 0, RLX)
                yield ("store", f"pay_a{s}", val, RLX)
                yield ("store", f"pay_b{s}", val, RLX)
                yield ("store", f"seq{s}", idx + 1, RLX)
            else:
                yield ("store", f"seq{s}", 0, RLX)
                yield ("fence", REL)
                yield ("store", f"pay_a{s}", val, RLX)
                yield ("store", f"pay_b{s}", val, RLX)
                yield ("fence", REL)
                yield ("store", f"seq{s}", idx + 1, REL)

    def reader(out: Dict[str, Any]):
        head = yield ("load", "head", ACQ)
        got = []
        for i in range(max(0, head - 2), head):
            s = i % 2
            seq = yield ("load", f"seq{s}", ACQ)
            if seq != i + 1:
                continue
            a = yield ("load", f"pay_a{s}", RLX)
            b = yield ("load", f"pay_b{s}", RLX)
            if broken != "missing-recheck":
                yield ("fence", ACQ)
                seq2 = yield ("load", f"seq{s}", ACQ)
                if seq2 != i + 1:
                    continue  # torn by a wrap: discard, as the C does
            got.append((i, a, b))
        out["got"] = got

    def check(ctx: WmmContext, out: Dict[str, Any],
              final: Dict[str, int]) -> None:
        for i, a, b in out.get("got", ()):
            want = 100 + i
            if a != want or b != want:
                ctx.report(
                    "wmm-no-torn-payload",
                    f"trace_ring: reader ACCEPTED slot for event {i} "
                    f"with payload ({a},{b}) != ({want},{want}) — "
                    f"torn/stale read survived the seqlock")

    init = {"head": 0}
    for s in (0, 1):
        init.update({f"seq{s}": 0, f"pay_a{s}": 0, f"pay_b{s}": 0})
    return Litmus(
        "trace_ring",
        "seqlock publish/wrap/read of the per-process trace event ring",
        "trace-slot", init, (writer, reader), check,
        ("wmm-no-torn-payload",))


# ---------------------------------------------------------------------------
# 2. region ledger charge/free as lock-free CAS (data-plane shape)
# ---------------------------------------------------------------------------

def make_ledger_cas(broken: str = "") -> Litmus:
    """Two tenants charge against one 100-byte device ledger
    (limit-checked CAS loop, the declared interposer-only shape); one
    frees its charge.  Conservation: the final ledger equals the
    surviving charges exactly — a lost update (the non-atomic broken
    variant) double-admits past the limit or double-frees."""
    limit = 100

    def charger(tag: str, nbytes: int, free_after: bool):
        def th(out: Dict[str, Any]):
            charged = False
            for _ in range(4):
                if broken == "plain-rmw":
                    v = yield ("load", "used", PLAIN)
                else:
                    v = yield ("load", "used", RLX)
                if v + nbytes > limit:
                    break
                if broken == "plain-rmw":
                    yield ("store", "used", v + nbytes, PLAIN)
                    ok = True
                else:
                    ok = yield ("cas", "used", v, v + nbytes, ACQ_REL)
                if ok:
                    charged = True
                    out[f"charged_{tag}"] = nbytes
                    break
            if charged and free_after:
                yield ("rmw", "used", -nbytes, ACQ_REL)
                out[f"freed_{tag}"] = nbytes
                if broken == "double-free":
                    # the release path runs again (the retry-after-
                    # partial-teardown bug class): same bytes returned
                    # twice, atomically — no race, pure conservation
                    yield ("rmw", "used", -nbytes, ACQ_REL)
        return th

    def check(ctx: WmmContext, out: Dict[str, Any],
              final: Dict[str, int]) -> None:
        expect = (out.get("charged_t0", 0) - out.get("freed_t0", 0)
                  + out.get("charged_t1", 0) - out.get("freed_t1", 0))
        if final["used"] != expect:
            ctx.report(
                "wmm-ledger-conserved",
                f"ledger_cas: final ledger {final['used']}B != "
                f"surviving charges {expect}B (lost update: double "
                f"admit or double free)")
        if final["used"] > limit:
            ctx.report(
                "wmm-ledger-conserved",
                f"ledger_cas: ledger {final['used']}B exceeds the "
                f"{limit}B limit — quota escaped the CAS admission")

    return Litmus(
        "ledger_cas",
        "lock-free HBM ledger charge/free with limit-checked CAS",
        "region-ledger", {"used": 0},
        (charger("t0", 60, True), charger("t1", 60, False)), check,
        ("wmm-ledger-conserved", "wmm-data-race"))


# ---------------------------------------------------------------------------
# 3. rate-lease pre-debit / burn / revoke-refund
# ---------------------------------------------------------------------------

def make_rate_lease(broken: str = "") -> Litmus:
    """A client pre-debits one 40µs quantum from the bucket, burns it
    in 15µs admissions against a shared lease balance, while the
    broker's revoke path concurrently swaps the balance to zero and
    refunds the remainder.  Burn+refund+residue must equal the one
    debited quantum — the plain-RMW broken variant loses the revoke's
    update and burns device time that was already refunded."""
    quantum, burn = 40, 15

    def client(out: Dict[str, Any]):
        yield ("rmw", "tokens", -quantum, ACQ_REL)  # pre-debit
        yield ("store", "lease", quantum, REL)
        burned = 0
        for _ in range(3):
            for _ in range(3):  # bounded CAS loop
                v = yield ("load", "lease",
                           PLAIN if broken == "plain-burn" else RLX)
                if v < burn:
                    break
                if broken == "plain-burn":
                    yield ("store", "lease", v - burn, PLAIN)
                    ok = True
                else:
                    ok = yield ("cas", "lease", v, v - burn, ACQ_REL)
                if ok:
                    burned += burn
                    break
        out["burned"] = burned

    def revoker(out: Dict[str, Any]):
        for _ in range(3):  # bounded CAS loop
            v = yield ("load", "lease", ACQ)
            if v <= 0:
                break
            ok = yield ("cas", "lease", v, 0, ACQ_REL)
            if ok:
                yield ("rmw", "tokens", v, REL)  # refund remainder
                out["refunded"] = v
                break

    def check(ctx: WmmContext, out: Dict[str, Any],
              final: Dict[str, int]) -> None:
        burned = out.get("burned", 0)
        refunded = out.get("refunded", 0)
        residue = final["lease"]
        if burned + refunded + residue != quantum:
            ctx.report(
                "wmm-lease-bounded",
                f"rate_lease: burned {burned} + refunded {refunded} + "
                f"residue {residue} != the one pre-debited quantum "
                f"{quantum}µs (unmetered device time)")
        if burned > quantum:
            ctx.report(
                "wmm-lease-bounded",
                f"rate_lease: burned {burned}µs exceeds the single "
                f"{quantum}µs quantum")

    return Litmus(
        "rate_lease",
        "lease pre-debit/burn racing the broker's revoke-and-refund",
        "rate-bucket", {"tokens": 100, "lease": 0},
        (client, revoker), check,
        ("wmm-lease-bounded", "wmm-data-race"))


# ---------------------------------------------------------------------------
# 4. burst-credit bank mint/spend
# ---------------------------------------------------------------------------

def make_credit_bank(broken: str = "") -> Litmus:
    """An idle-accrual minter tops the bank up (capped CAS) while a
    spender draws it down; the balance must stay within [0, cap] and
    spends within mints.  The plain-mint broken variant writes the
    bank non-atomically and uncapped — credit minted from nothing."""
    cap = 50

    def minter(out: Dict[str, Any]):
        minted = 0
        for _ in range(3):
            for _ in range(3):
                if broken == "plain-mint":
                    v = yield ("load", "credit", PLAIN)
                    yield ("store", "credit", v + 30, PLAIN)
                    minted += 30
                    break
                v = yield ("load", "credit", RLX)
                nv = min(v + 30, cap)
                if nv == v:
                    break
                ok = yield ("cas", "credit", v, nv, ACQ_REL)
                if ok:
                    minted += nv - v
                    break
        out["minted"] = minted

    def spender(out: Dict[str, Any]):
        spent = 0
        for _ in range(2):
            for _ in range(3):
                v = yield ("load", "credit", RLX)
                if v < 20:
                    break
                ok = yield ("cas", "credit", v, v - 20, ACQ_REL)
                if ok:
                    spent += 20
                    break
        out["spent"] = spent

    def check(ctx: WmmContext, out: Dict[str, Any],
              final: Dict[str, int]) -> None:
        bal = final["credit"]
        minted = out.get("minted", 0)
        spent = out.get("spent", 0)
        if bal < 0 or bal > cap:
            ctx.report(
                "wmm-credit-bounds",
                f"credit_bank: balance {bal}µs outside [0, {cap}] "
                f"(cap bypassed or double spend)")
        if bal != minted - spent:
            ctx.report(
                "wmm-credit-bounds",
                f"credit_bank: balance {bal} != minted {minted} - "
                f"spent {spent} (credit minted from nothing or a "
                f"lost update)")

    return Litmus(
        "credit_bank",
        "burst-credit mint (capped) racing spend over shared atomics",
        "credit-bank", {"credit": 0}, (minter, spender), check,
        ("wmm-credit-bounds", "wmm-data-race"))


# ---------------------------------------------------------------------------
# 5. degraded-mode quota read with the broker gone
# ---------------------------------------------------------------------------

def make_degraded_quota(broken: str = "") -> Litmus:
    """The broker resizes a tenant's quota and may be SIGKILLed after
    ANY instruction (crash choice points); the degraded-mode client
    keeps admitting against the crash-atomic fields.  Whatever the cut
    the client must observe the OLD or the NEW limit — the two-word
    broken variant splits the limit across two words and the client
    can combine halves of different epochs into a limit nobody ever
    granted (the silent-corruption class the crash-atomic single-word
    rule exists for)."""
    old, new = 14, 28  # both decimal "words" differ between epochs

    def broker(out: Dict[str, Any]):
        if broken == "two-word":
            die = yield ("choice", 2)
            if die:
                return
            yield ("store", "limit_lo", new % 10, REL)
            die = yield ("choice", 2)
            if die:
                return
            yield ("store", "limit_hi", new // 10, REL)
        else:
            die = yield ("choice", 2)
            if die:
                return
            yield ("store", "limit", new, REL)
        die = yield ("choice", 2)
        if die:
            return
        yield ("store", "epoch", 2, REL)

    def client(out: Dict[str, Any]):
        admits = 0
        seen = []
        for _ in range(3):
            if broken == "two-word":
                hi = yield ("load", "limit_hi", ACQ)
                lo = yield ("load", "limit_lo", ACQ)
                lim = hi * 10 + lo
            else:
                lim = yield ("load", "limit", ACQ)
            seen.append(lim)
            used = yield ("load", "used", ACQ)
            if used + 2 <= lim:
                yield ("rmw", "used", 2, ACQ_REL)
                admits += 1
        out["admits"] = admits
        out["seen"] = seen

    def check(ctx: WmmContext, out: Dict[str, Any],
              final: Dict[str, int]) -> None:
        for lim in out.get("seen", ()):
            if lim not in (old, new):
                ctx.report(
                    "wmm-crash-atomic",
                    f"degraded_quota: client observed limit {lim} — "
                    f"neither the old grant {old} nor the new {new} "
                    f"(torn quota under broker death)")
        if final["used"] > new:
            ctx.report(
                "wmm-crash-atomic",
                f"degraded_quota: admitted {final['used']}B against a "
                f"max grant of {new}B — the quota stopped biting with "
                f"the broker gone")

    init = {"limit": old, "limit_lo": old % 10, "limit_hi": old // 10,
            "used": 0, "epoch": 1}
    return Litmus(
        "degraded_quota",
        "degraded-mode quota admission while the broker dies mid-resize",
        "degraded-ledger", init, (broker, client), check,
        ("wmm-crash-atomic",))


# ---------------------------------------------------------------------------
# 6. Interposer-only shm execute ring (SPSC + credit gate, vtpu-fastlane)
# ---------------------------------------------------------------------------

def make_exec_ring(broken: str = "") -> Litmus:
    """The vtpu-fastlane data plane — spec'd and verified here one PR
    BEFORE ``vtpu_exec_submit``/``take``/``complete`` existed, now a
    faithful miniature of the IMPLEMENTED writer/consumer shapes in
    ``native/vtpucore/vtpu_core.cc`` (the static shape check in
    tools/analyze/atomics.py proves the C follows the same event
    order).  Producer: acq_rel fetch_sub credit gate (undo on refusal),
    acquire load of headc (the slot-reuse gate), relaxed payload fill,
    release tail publish.  Consumer: acquire tail, relaxed copy,
    release headc publish, acq_rel credit return.  FIFO +
    no-torn-descriptor + credit conservation must hold under every
    exploration.  Broken variants: ``relaxed-tail`` publishes the tail
    relaxed (the consumer can execute words never made visible);
    ``skip-headc-gate`` drops the slot-reuse gate while the credit
    counter is crash-torn one high — the wrap overwrites a descriptor
    the consumer has not republished (exactly the bug class the gate
    exists for)."""
    items, capacity = 3, 2
    # The skip-gate variant models a crash-torn credit counter (one
    # credit too many): with the gate present that is harmless — the
    # gate refuses the early wrap — with it skipped, an unconsumed
    # slot is overwritten.
    init_credits = capacity + (1 if broken == "skip-headc-gate" else 0)

    def producer(out: Dict[str, Any]):
        produced = 0
        for i in range(items):
            got_credit = False
            for _ in range(6):  # bounded credit-gate spin
                c = yield ("rmw", "credits", -1, ACQ_REL)
                if c > 0:       # fetch_sub returns the OLD value
                    got_credit = True
                    break
                yield ("rmw", "credits", 1, ACQ_REL)  # undo; refused
            if not got_credit:
                break
            if broken == "skip-headc-gate":
                ok_slot = True
            else:
                ok_slot = False
                for _ in range(6):  # bounded ring-full spin
                    h = yield ("load", "headc", ACQ)
                    if i - h < capacity:
                        ok_slot = True
                        break
            if not ok_slot:
                # Abort: the gate credit goes back (the implemented
                # abort path — a taken credit never strands).
                yield ("rmw", "credits", 1, ACQ_REL)
                break
            s = i % capacity
            yield ("store", f"desc_a{s}", 200 + i, RLX)
            yield ("store", f"desc_b{s}", 200 + i, RLX)
            if broken == "relaxed-tail":
                yield ("store", "tail", i + 1, RLX)
            else:
                yield ("store", "tail", i + 1, REL)
            produced += 1
        out["produced"] = produced

    def consumer(out: Dict[str, Any]):
        done = []
        for i in range(items):
            ready = False
            for _ in range(6):  # bounded not-yet-published spin
                t = yield ("load", "tail", ACQ)
                if t > i:
                    ready = True
                    break
            if not ready:
                break
            s = i % capacity
            a = yield ("load", f"desc_a{s}", RLX)
            b = yield ("load", f"desc_b{s}", RLX)
            done.append((i, a, b))
            yield ("store", "headc", i + 1, REL)
            yield ("rmw", "credits", 1, ACQ_REL)
        out["done"] = done

    def check(ctx: WmmContext, out: Dict[str, Any],
              final: Dict[str, int]) -> None:
        done = out.get("done", ())
        for pos, (i, a, b) in enumerate(done):
            if i != pos:
                ctx.report(
                    "wmm-ring-fifo",
                    f"exec_ring: descriptor {i} consumed at position "
                    f"{pos} — FIFO order broken")
            want = 200 + i
            if a != want or b != want:
                ctx.report(
                    "wmm-ring-fifo",
                    f"exec_ring: consumer EXECUTED descriptor {i} "
                    f"with words ({a},{b}) != ({want},{want}) — "
                    f"unpublished/torn/overwritten descriptor crossed "
                    f"the ring")
        inflight = out.get("produced", 0) - len(done)
        if final["credits"] + inflight != init_credits:
            ctx.report(
                "wmm-ring-fifo",
                f"exec_ring: credit gate leaked — {final['credits']} "
                f"credits + {inflight} in flight != the seeded "
                f"{init_credits}")

    init = {"tail": 0, "headc": 0, "credits": init_credits}
    for s in range(capacity):
        init.update({f"desc_a{s}": 0, f"desc_b{s}": 0})
    return Litmus(
        "exec_ring",
        "interposer-only SPSC execute ring + credit gate "
        "(vtpu-fastlane; shape-matched to vtpu_exec_submit/take/"
        "complete)",
        "exec-ring", init, (producer, consumer), check,
        ("wmm-ring-fifo", "wmm-no-torn-payload"))


# ---------------------------------------------------------------------------
# 7. Multi-chip completion vector (per-chip rings + completion join,
#    vtpu-fastlane-everywhere)
# ---------------------------------------------------------------------------

def make_multi_ring(broken: str = "") -> Litmus:
    """A sharded lane's completion-join shape (vtpu_core.h
    ``publish: ExecRing.cvec release -> consume: acquire``): the
    producer submits one descriptor PER CHIP RING; the LEAD chip's
    consumer executes (binds the outputs — modeled as the ``res``
    words), publishes its headc (release) and then its completion-
    vector slot ``cvec0`` (release); the FOLLOWER chip's consumer
    completes its ring only after an acquire read of ``cvec0`` and
    publishes ``cvec1`` (release); the JOINER (the client's
    ``cvec_wait``) acquire-sweeps the vector and must then observe
    every output the lead bound — a join can never release a result
    whose binds are not yet visible.  Broken variant:
    ``relaxed-cvec`` publishes the lead's vector slot relaxed — the
    joiner can join a completion whose output words it cannot see
    (exactly the bug class the declared release order exists for)."""
    items = 2
    cvec_pub = RLX if broken == "relaxed-cvec" else REL

    def producer(out: Dict[str, Any]):
        for i in range(items):
            # One descriptor per chip ring, same seq stream (payload
            # relaxed, tail release — the exec_ring litmus already
            # polices the full gate shape; this one isolates the
            # join).
            yield ("store", f"descL{i}", 100 + i, RLX)
            yield ("store", "tailL", i + 1, REL)
            yield ("store", f"descF{i}", 300 + i, RLX)
            yield ("store", "tailF", i + 1, REL)

    def lead(out: Dict[str, Any]):
        done = 0
        for i in range(items):
            ready = False
            for _ in range(6):
                t = yield ("load", "tailL", ACQ)
                if t > i:
                    ready = True
                    break
            if not ready:
                break
            v = yield ("load", f"descL{i}", RLX)
            # The output bind the joiner must observe.
            yield ("store", f"res{i}", v, RLX)
            yield ("store", "headcL", i + 1, REL)
            yield ("store", "cvec0", i + 1, cvec_pub)
            done += 1
        out["lead_done"] = done

    def follower(out: Dict[str, Any]):
        done = 0
        for i in range(items):
            ready = False
            for _ in range(6):
                c = yield ("load", "cvec0", ACQ)
                if c > i:
                    ready = True
                    break
            if not ready:
                break
            yield ("store", "headcF", i + 1, REL)
            yield ("store", "cvec1", i + 1, REL)
            done += 1
        out["follower_done"] = done

    def joiner(out: Dict[str, Any]):
        joined = []
        for i in range(items):
            ready = False
            for _ in range(8):
                c1 = yield ("load", "cvec1", ACQ)
                if c1 > i:
                    ready = True
                    break
            if not ready:
                break
            r = yield ("load", f"res{i}", RLX)
            joined.append((i, r))
        out["joined"] = joined

    def check(ctx: WmmContext, out: Dict[str, Any],
              final: Dict[str, int]) -> None:
        for i, r in out.get("joined", ()):
            if r != 100 + i:
                ctx.report(
                    "wmm-no-torn-payload",
                    f"multi_ring: joiner released seq {i} with the "
                    f"lead's output bind invisible (res={r} != "
                    f"{100 + i}) — the completion-vector join is "
                    f"not a synchronization point")

    init = {"tailL": 0, "tailF": 0, "headcL": 0, "headcF": 0,
            "cvec0": 0, "cvec1": 0}
    for i in range(items):
        init.update({f"descL{i}": 0, f"descF{i}": 0, f"res{i}": 0})
    return Litmus(
        "multi_ring",
        "multi-chip per-chip rings: sharded submit + completion-"
        "vector join (lead publishes cvec release, follower and "
        "client consume acquire)",
        "exec-ring", init, (producer, lead, follower, joiner), check,
        ("wmm-no-torn-payload", "wmm-ring-fifo"))


FACTORIES: Tuple[Callable[..., Litmus], ...] = (
    make_trace_ring, make_ledger_cas, make_rate_lease,
    make_credit_bank, make_degraded_quota, make_exec_ring,
    make_multi_ring)

LITMUS: Tuple[Litmus, ...] = tuple(f() for f in FACTORIES)


def get(name: str) -> Litmus:
    for lt in LITMUS:
        if lt.name == name:
            return lt
    raise KeyError(f"unknown litmus {name!r} "
                   f"(have: {[x.name for x in LITMUS]})")
