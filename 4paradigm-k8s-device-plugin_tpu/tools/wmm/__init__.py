"""vtpu-wmm — weak-memory-model checking of the shared-region
lock-free protocols (docs/ANALYSIS.md "Weak memory model").

The dynamic half of the vtpu-wmm pair (the static half is
``tools/analyze/atomics.py``): an operational C11-ish simulator
(per-location message histories + per-thread views, the promise-free
view-based semantics) that exhaustively explores litmus programs
modeling the REAL shared-region protocols — trace-ring seqlock
publish/wrap/read, region-ledger CAS charge/free, rate-lease burn,
burst-credit mint/spend, degraded-mode quota reads with the broker
dead mid-update, and the PLANNED interposer-only shm execute ring
(ROADMAP item 2) — and holds every reachable outcome to the ``wmm``
rows of the ``tools/mc/invariants.py`` registry.

Run as ``python -m vtpu.tools.wmm`` or ``vtpu-smi wmm [--smoke]``;
``--selfcheck`` proves each deliberately weakened protocol variant is
caught.  Stdlib-only; deterministic; explored-execution counts are
floor-gated in CI like the mc job.
"""

from .cli import main  # noqa: F401
