"""vtpu-wmm operational weak-memory engine.

A small C11-ish memory model explored exhaustively: the machinery that
lets the litmus programs in ``litmus.py`` exhibit the reorderings a
weakly-ordered CPU (arm64) is allowed under the orders the code
actually wrote — not the orders the author hoped for.

The model is the classic *view-based* operational semantics (the
promise-free core of Kang et al.'s "A Promising Semantics for
Relaxed-Memory Concurrency", POPL'17 — also the shape tools like
herd7's operational companions use):

  - memory is, per location, an append-only list of **messages**
    ``(ts, value, view)`` — every store ever made, never just "the"
    current value;
  - each thread carries a **current view** (per-location timestamp
    floor): a load may read ANY message at or above the floor, which
    is exactly how a stale cache line / store-buffer read manifests;
  - release stores attach the writer's whole view to the message;
    acquire loads join the message's view into the reader's — the
    message-passing guarantee.  Relaxed accesses move only the one
    location's floor; the stale-payload-behind-a-fresh-flag bug falls
    straight out;
  - release fences snapshot the thread view into the view attached to
    LATER relaxed stores; acquire fences fold the views of earlier
    relaxed reads into the thread view.  This models the Linux-style
    seqlock discipline vtpu_core.cc uses (fence; relaxed payload;
    fence; release publish) faithfully: drop a fence in the litmus and
    the torn/stale read becomes reachable;
  - RMWs read the NEWEST message and append adjacently (atomicity),
    carrying the read message's view forward (C11 release sequences);
  - **plain** (non-atomic) accesses are relaxed accesses that
    additionally report a data race whenever the access is
    nondeterministic — a plain load that could read more than one
    message, or a plain store while an unobserved concurrent write
    exists, is exactly a C11 data race (undefined behavior), so the
    engine flags it instead of picking a value and hoping.

Approximations (kept one-sided — the model may miss exotic behaviors,
it does not invent impossible ones): stores append at the end of a
location's history (no interleaved timestamps, which hides some 2+2W
shapes irrelevant to our single-writer/CAS protocols), and there is a
single global SC order for ``sc`` accesses.

Exploration is a deterministic DFS over three kinds of decisions —
which thread steps, which readable message a load observes, and
explicit program ``choice`` points (crash injection) — with a
CHESS-style preemption bound on the scheduling decisions only
(message and choice alternatives are always fully explored).  Same
program + same budgets => same executions, bit for bit; CI floor-gates
the explored count like the mc job does.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Generator, List, Optional, Tuple

# Memory orders.  ``PLAIN`` is a non-atomic access (race-checked);
# everything else maps onto the C11 order of the same name.
PLAIN = "plain"
RLX = "rlx"
ACQ = "acq"
REL = "rel"
ACQ_REL = "acq_rel"
SC = "sc"

_ACQ_ORDERS = (ACQ, ACQ_REL, SC)
_REL_ORDERS = (REL, ACQ_REL, SC)

DEFAULT_MAX_EXECUTIONS = 4000
DEFAULT_PREEMPTION_BOUND = 2
DEFAULT_MAX_STEPS = 2000


def budget_env(name: str, default: int) -> int:
    """Budget knob with a VTPU_WMM_* env override (docs/FLAGS.md)."""
    raw = os.environ.get(name, "")
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


class WmmContext:
    """Violation sink shared by the engine, the litmus ``check``
    functions and the invariant registry: everything lands in a named
    bucket matching one ``tools/mc/invariants.py`` wmm row, and
    ``run_checks("wmm", "litmus", ctx)`` drains the buckets."""

    def __init__(self) -> None:
        self.buckets: Dict[str, List[str]] = {}

    def report(self, row: str, msg: str) -> None:
        self.buckets.setdefault(row, []).append(msg)

    def take(self, row: str) -> List[str]:
        return self.buckets.pop(row, [])

    def pending(self) -> int:
        return sum(len(v) for v in self.buckets.values())


@dataclass
class Msg:
    ts: int
    val: int
    view: Dict[str, int]


def _join(dst: Dict[str, int], src: Dict[str, int]) -> None:
    for k, v in src.items():
        if v > dst.get(k, 0):
            dst[k] = v


class _Thread:
    def __init__(self, tid: int, gen: Generator) -> None:
        self.tid = tid
        self.gen = gen
        self.cur: Dict[str, int] = {}
        self.acq: Dict[str, int] = {}
        self.rel: Dict[str, int] = {}
        self.pending: Optional[Tuple] = None
        self.done = False

    def advance(self, result: Any) -> None:
        """Feed the last op's result in; fetch the next op."""
        try:
            self.pending = self.gen.send(result)
        except StopIteration:
            self.pending = None
            self.done = True


@dataclass
class _Node:
    """One decision point along the current execution."""
    kind: str                  # "sched" | "msg" | "choice"
    alts: List[int]
    chosen: int
    prev: Optional[int] = None   # sched: thread that ran the last slice
    used_before: int = 0         # sched: preemptions consumed before here
    tried: set = field(default_factory=set)

    def cost(self, alt: int) -> int:
        if self.kind != "sched":
            return 0
        return 1 if (self.prev is not None and self.prev in self.alts
                     and alt != self.prev) else 0


class ReplayDivergence(RuntimeError):
    pass


@dataclass
class LitmusStats:
    name: str = ""
    executions: int = 0
    decisions: int = 0
    truncated: int = 0
    violations: List[str] = field(default_factory=list)
    # decision script that produced the first violation
    witness: Optional[List[int]] = None


class Explorer:
    """Exhaustive DFS over one litmus program's decision tree."""

    def __init__(self, litmus: "Any", *,
                 max_executions: Optional[int] = None,
                 preemption_bound: Optional[int] = None,
                 max_steps: Optional[int] = None) -> None:
        self.litmus = litmus
        self.max_executions = (
            max_executions if max_executions is not None
            else budget_env("VTPU_WMM_MAX_EXECUTIONS",
                            DEFAULT_MAX_EXECUTIONS))
        self.preemption_bound = (
            preemption_bound if preemption_bound is not None
            else budget_env("VTPU_WMM_PREEMPTIONS",
                            DEFAULT_PREEMPTION_BOUND))
        self.max_steps = (max_steps if max_steps is not None
                          else budget_env("VTPU_WMM_MAX_STEPS",
                                          DEFAULT_MAX_STEPS))
        self.stats = LitmusStats(name=litmus.name)

    # -- one execution -----------------------------------------------------

    def _run_once(self, script: List[int], nodes: List[_Node],
                  ctx: WmmContext) -> None:
        mem: Dict[str, List[Msg]] = {
            loc: [Msg(0, val, {})]
            for loc, val in self.litmus.init.items()}
        out: Dict[str, Any] = {}
        threads = [_Thread(i, fn(out))
                   for i, fn in enumerate(self.litmus.threads)]
        for t in threads:
            t.advance(None)

        depth = 0

        def choose(kind: str, alts: List[int],
                   prev: Optional[int] = None) -> int:
            nonlocal depth
            self.stats.decisions += 1
            if depth < len(nodes):
                node = nodes[depth]
                if node.chosen not in alts:
                    raise ReplayDivergence(
                        f"{self.litmus.name}: decision {depth} scripted "
                        f"{node.chosen}, alternatives now {alts}")
                node.alts = list(alts)
                depth += 1
                return node.chosen
            # Past the script: default policy, recorded as a new node.
            parent = None
            for n in reversed(nodes):
                if n.kind == "sched":
                    parent = n
                    break
            if kind == "sched":
                used = (parent.used_before + parent.cost(parent.chosen)
                        if parent else 0)
                pick = prev if (prev is not None and prev in alts) \
                    else alts[0]
                node = _Node(kind, list(alts), pick, prev=prev,
                             used_before=used)
            else:
                # Loads default to the NEWEST readable message (the
                # SC-like execution comes first; stale reads are the
                # backtracked alternatives).
                pick = alts[-1] if kind == "msg" else alts[0]
                node = _Node(kind, list(alts), pick)
            node.tried.add(pick)
            nodes.append(node)
            depth += 1
            return pick

        def enabled(t: _Thread) -> bool:
            if t.done or t.pending is None:
                return False
            op = t.pending
            if op[0] == "lock":
                return mem[op[1]][-1].val == 0
            return True

        last_tid: Optional[int] = None
        steps = 0
        while True:
            live = [t for t in threads if enabled(t)]
            if not live:
                break
            steps += 1
            if steps > self.max_steps:
                self.stats.truncated += 1
                break
            tid = choose("sched", [t.tid for t in live], prev=last_tid)
            last_tid = tid
            th = threads[tid]
            result = self._perform(th, th.pending, mem, choose, ctx)
            th.advance(result)

        final = {loc: msgs[-1].val for loc, msgs in mem.items()}
        self.litmus.check(ctx, out, final)

    def _perform(self, th: _Thread, op: Tuple, mem: Dict[str, List[Msg]],
                 choose: Callable, ctx: WmmContext) -> Any:
        kind = op[0]
        if kind == "load":
            _, loc, order = op
            floor = th.cur.get(loc, 0)
            readable = [m for m in mem[loc] if m.ts >= floor]
            if order == PLAIN and len(readable) > 1:
                ctx.report(
                    "wmm-data-race",
                    f"{self.litmus.name}: plain load of `{loc}` by "
                    f"thread {th.tid} races a concurrent write "
                    f"({len(readable)} values observable — C11 "
                    f"undefined behavior)")
            if len(readable) > 1:
                idx = choose("msg", list(range(len(readable))))
            else:
                idx = 0
            m = readable[idx]
            th.cur[loc] = max(floor, m.ts)
            if order in _ACQ_ORDERS:
                _join(th.cur, m.view)
            else:
                _join(th.acq, m.view)
                if m.ts > th.acq.get(loc, 0):
                    th.acq[loc] = m.ts
            return m.val
        if kind == "store":
            _, loc, val, order = op
            msgs = mem[loc]
            if order == PLAIN and msgs[-1].ts > th.cur.get(loc, 0):
                ctx.report(
                    "wmm-data-race",
                    f"{self.litmus.name}: plain store to `{loc}` by "
                    f"thread {th.tid} races an unobserved concurrent "
                    f"write (C11 undefined behavior)")
            ts = msgs[-1].ts + 1
            base = th.cur if order in _REL_ORDERS else th.rel
            view = dict(base)
            view[loc] = ts
            msgs.append(Msg(ts, val, view))
            th.cur[loc] = ts
            return None
        if kind in ("rmw", "cas"):
            loc = op[1]
            order = op[-1]
            m = mem[loc][-1]
            success = True
            if kind == "cas" and m.val != op[2]:
                success = False
            if order in _ACQ_ORDERS or (not success and order != PLAIN):
                _join(th.cur, m.view)
            th.cur[loc] = max(th.cur.get(loc, 0), m.ts)
            if not success:
                return False
            newval = m.val + op[2] if kind == "rmw" else op[3]
            ts = m.ts + 1
            base = th.cur if order in _REL_ORDERS else th.rel
            view = dict(base)
            _join(view, m.view)  # release sequence: carry forward
            view[loc] = ts
            mem[loc].append(Msg(ts, newval, view))
            th.cur[loc] = ts
            return m.val if kind == "rmw" else True
        if kind == "fence":
            order = op[1]
            if order in _ACQ_ORDERS:
                _join(th.cur, th.acq)
            if order in _REL_ORDERS:
                th.rel = dict(th.cur)
            return None
        if kind == "lock":
            loc = op[1]
            m = mem[loc][-1]
            _join(th.cur, m.view)  # acquire
            ts = m.ts + 1
            view = dict(th.cur)
            _join(view, m.view)
            view[loc] = ts
            mem[loc].append(Msg(ts, 1, view))
            th.cur[loc] = ts
            return None
        if kind == "unlock":
            loc = op[1]
            ts = mem[loc][-1].ts + 1
            view = dict(th.cur)  # release
            view[loc] = ts
            mem[loc].append(Msg(ts, 0, view))
            th.cur[loc] = ts
            return None
        if kind == "choice":
            return choose("choice", list(range(op[1])))
        raise ValueError(f"unknown wmm op {op!r}")

    # -- DFS over executions -----------------------------------------------

    def explore(self, ctx: Optional[WmmContext] = None) -> LitmusStats:
        ctx = ctx if ctx is not None else WmmContext()
        nodes: List[_Node] = []
        script: List[int] = []
        while True:
            before = ctx.pending()
            try:
                self._run_once(script, nodes, ctx)
            except ReplayDivergence as e:
                self.stats.violations.append(f"[determinism] {e}")
                self.stats.witness = list(script)
                break
            self.stats.executions += 1
            if ctx.pending() > before and self.stats.witness is None:
                self.stats.witness = [n.chosen for n in nodes]
            if self.stats.executions >= self.max_executions:
                break
            # Backtrack: deepest node with an unexplored,
            # budget-feasible alternative.
            nxt = None
            while nodes:
                node = nodes[-1]
                feasible = [
                    a for a in node.alts
                    if a not in node.tried
                    and node.used_before + node.cost(a)
                    <= self.preemption_bound]
                if feasible:
                    a = feasible[0]
                    node.tried.add(a)
                    new = _Node(node.kind, list(node.alts), a,
                                prev=node.prev,
                                used_before=node.used_before)
                    new.tried = node.tried  # shared explored set
                    nodes[-1] = new
                    nxt = [n.chosen for n in nodes]
                    break
                nodes.pop()
            if nxt is None:
                break  # decision space exhausted
            script = nxt
            nodes = nodes[:len(script)]
        from ..mc import invariants as inv_registry
        self.stats.violations.extend(
            inv_registry.run_checks("wmm", "litmus", ctx))
        return self.stats


def explore_litmus(litmus: Any, **kw: Any) -> LitmusStats:
    return Explorer(litmus, **kw).explore()
