"""Protocol-verb exhaustiveness checker.

The wire protocol is three hand-maintained halves — verb constants in
``runtime/protocol.py``, dispatch arms in the broker
(``TenantSession._serve`` / ``AdminSession.handle``), and senders in
``runtime/client.py`` / ``tools/vtpu_smi.py``.  Nothing ties them
together at runtime (an unknown verb just earns BAD_KIND), so a new
verb can silently ship with no broker arm or no client binding.  This
checker proves, per verb:

  - membership in exactly the protocol registries
    (``TENANT_VERBS`` / ``ADMIN_VERBS`` / ``BIND_FREE_VERBS``);
  - a dispatch arm on every socket that serves it;
  - a sender binding (client for tenant verbs, vtpu-smi for admin);
  - bind-free verbs answered BEFORE the NO_HELLO guard on the tenant
    socket and present on the admin socket too (the no-wedge probe
    contract, ADVICE r5 #2).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from . import Finding, read_text, PKG_NAME

PROTOCOL = f"{PKG_NAME}/runtime/protocol.py"
SERVER = f"{PKG_NAME}/runtime/server.py"
CLIENT = f"{PKG_NAME}/runtime/client.py"
SMI = f"{PKG_NAME}/tools/vtpu_smi.py"


def parse_protocol(src: str, path: str = PROTOCOL
                   ) -> Tuple[Dict[str, int], Dict[str, Set[str]],
                              List[Finding]]:
    """(verb constants {NAME: line}, registries {REGISTRY: {NAME}},
    findings)."""
    findings: List[Finding] = []
    verbs: Dict[str, int] = {}
    registries: Dict[str, Set[str]] = {}
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return {}, {}, [Finding("verbs", path, e.lineno or 1,
                                f"syntax error: {e.msg}")]
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not isinstance(tgt, ast.Name) or not tgt.id.isupper():
            continue
        val = node.value
        if isinstance(val, ast.Constant) and isinstance(val.value, str):
            verbs[tgt.id] = node.lineno
        elif isinstance(val, (ast.Tuple, ast.List)) and \
                tgt.id.endswith("_VERBS"):
            names = set()
            for el in val.elts:
                if isinstance(el, ast.Name):
                    names.add(el.id)
                else:
                    findings.append(Finding(
                        "verbs", path, el.lineno,
                        f"{tgt.id} entry is not a verb constant name"))
            registries[tgt.id] = names
    for reg in ("TENANT_VERBS", "ADMIN_VERBS", "BIND_FREE_VERBS"):
        if reg not in registries:
            findings.append(Finding(
                "verbs", path, 1,
                f"protocol registry {reg} is missing"))
            registries[reg] = set()
    known = registries["TENANT_VERBS"] | registries["ADMIN_VERBS"]
    for name, line in verbs.items():
        if name not in known:
            findings.append(Finding(
                "verbs", path, line,
                f"verb {name} is in neither TENANT_VERBS nor "
                f"ADMIN_VERBS"))
    for reg, names in registries.items():
        for name in names:
            if name not in verbs:
                findings.append(Finding(
                    "verbs", path, 1,
                    f"{reg} names unknown verb constant {name}"))
    for name in registries["BIND_FREE_VERBS"]:
        for reg in ("TENANT_VERBS", "ADMIN_VERBS"):
            if name in verbs and name not in registries[reg]:
                findings.append(Finding(
                    "verbs", path, verbs.get(name, 1),
                    f"bind-free verb {name} must be served on both "
                    f"sockets but is missing from {reg}"))
    return verbs, registries, findings


def _find_func(tree: ast.AST, cls: str, fn: str
               ) -> Optional[ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == cls:
            for sub in node.body:
                if isinstance(sub, ast.FunctionDef) and sub.name == fn:
                    return sub
    return None


def dispatch_arms(fn: ast.FunctionDef) -> Dict[str, int]:
    """{verb constant name: first line} for every ``kind == P.X`` /
    ``kind in (P.X, ...)`` comparison in the handler."""
    arms: Dict[str, int] = {}
    for node in ast.walk(fn):
        if not isinstance(node, ast.Compare):
            continue
        involved = [node.left] + list(node.comparators)
        names = []
        for part in involved:
            if isinstance(part, ast.Attribute) and \
                    isinstance(part.value, ast.Name) and \
                    part.value.id == "P":
                names.append(part.attr)
            elif isinstance(part, (ast.Tuple, ast.List)):
                for el in part.elts:
                    if isinstance(el, ast.Attribute) and \
                            isinstance(el.value, ast.Name) and \
                            el.value.id == "P":
                        names.append(el.attr)
        for name in names:
            arms.setdefault(name, node.lineno)
    return arms


def no_hello_line(fn: ast.FunctionDef) -> Optional[int]:
    """Line of the ``NO_HELLO`` bind guard in _serve."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Constant) and node.value == "NO_HELLO":
            return node.lineno
    return None


def sender_bindings(src: str) -> Set[str]:
    """Verb constants sent by a module: dict literals carrying
    ``"kind": P.X``."""
    out: Set[str] = set()
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return out
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        for k, v in zip(node.keys, node.values):
            if isinstance(k, ast.Constant) and k.value == "kind" and \
                    isinstance(v, ast.Attribute) and \
                    isinstance(v.value, ast.Name) and v.value.id == "P":
                out.add(v.attr)
    return out


def check_texts(protocol_src: str, server_src: str, client_src: str,
                smi_src: str) -> List[Finding]:
    verbs, registries, findings = parse_protocol(protocol_src)
    if not verbs:
        return findings
    try:
        server_tree = ast.parse(server_src)
    except SyntaxError as e:
        return findings + [Finding("verbs", SERVER, e.lineno or 1,
                                   f"syntax error: {e.msg}")]
    serve = _find_func(server_tree, "TenantSession", "_serve")
    admin = _find_func(server_tree, "AdminSession", "handle")
    if serve is None or admin is None:
        return findings + [Finding(
            "verbs", SERVER, 1,
            "cannot locate TenantSession._serve / AdminSession.handle")]
    tenant_arms = dispatch_arms(serve)
    admin_arms = dispatch_arms(admin)
    for name in sorted(registries["TENANT_VERBS"]):
        if name not in tenant_arms:
            findings.append(Finding(
                "verbs", SERVER, serve.lineno,
                f"tenant verb {name} has no dispatch arm in "
                f"TenantSession._serve"))
    for name in sorted(registries["ADMIN_VERBS"]):
        if name not in admin_arms:
            findings.append(Finding(
                "verbs", SERVER, admin.lineno,
                f"admin verb {name} has no dispatch arm in "
                f"AdminSession.handle"))
    guard = no_hello_line(serve)
    if guard is None:
        findings.append(Finding(
            "verbs", SERVER, serve.lineno,
            "cannot locate the NO_HELLO bind guard in _serve"))
    else:
        for name in sorted(registries["BIND_FREE_VERBS"]):
            line = tenant_arms.get(name)
            if line is not None and line > guard:
                findings.append(Finding(
                    "verbs", SERVER, line,
                    f"bind-free verb {name} is dispatched AFTER the "
                    f"NO_HELLO guard (line {guard}) — an unbound probe "
                    f"would be refused"))
    client_sends = sender_bindings(client_src)
    for name in sorted(registries["TENANT_VERBS"]):
        if name not in client_sends:
            findings.append(Finding(
                "verbs", CLIENT, 1,
                f"tenant verb {name} has no client binding in "
                f"runtime/client.py"))
    smi_sends = sender_bindings(smi_src)
    for name in sorted(registries["ADMIN_VERBS"]):
        # STATS/TRACE ride the main socket from vtpu-smi too; any P.X
        # dict in the module counts as the operator binding.
        if name not in smi_sends:
            findings.append(Finding(
                "verbs", SMI, 1,
                f"admin verb {name} has no vtpu-smi binding"))
    return findings


def check(root: str) -> List[Finding]:
    srcs = {rel: read_text(root, rel)
            for rel in (PROTOCOL, SERVER, CLIENT, SMI)}
    if any(v is None for v in srcs.values()):
        return []
    return check_texts(srcs[PROTOCOL], srcs[SERVER], srcs[CLIENT],
                       srcs[SMI])
