"""Protocol-verb exhaustiveness checker.

The wire protocol is three hand-maintained halves — verb constants in
``runtime/protocol.py``, dispatch arms in the broker
(``TenantSession._serve`` / ``AdminSession.handle``), and senders in
``runtime/client.py`` / ``tools/vtpu_smi.py``.  Nothing ties them
together at runtime (an unknown verb just earns BAD_KIND), so a new
verb can silently ship with no broker arm or no client binding.  This
checker proves, per verb:

  - membership in exactly the protocol registries
    (``TENANT_VERBS`` / ``ADMIN_VERBS`` / ``BIND_FREE_VERBS``);
  - a dispatch arm on every socket that serves it;
  - a sender binding (client for tenant verbs, vtpu-smi for admin);
  - bind-free verbs answered BEFORE the NO_HELLO guard on the tenant
    socket and present on the admin socket too (the no-wedge probe
    contract, ADVICE r5 #2).

vtpu-metricsd's gRPC surface has the same three-hands problem (the
``METRICSD_RPCS`` registry in ``metricsd/__init__.py``, the hand-written
stub/servicer glue in ``proto/tpu_metrics_grpc.py``, and the
implementation in ``metricsd/server.py``), so the same exhaustiveness is
proven for it: every registered RPC must have a stub binding, a glue
servicer method, a registration-handler entry AND an implementation
override; an implemented-but-unregistered RPC fails too.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from . import Finding, read_text, PKG_NAME

PROTOCOL = f"{PKG_NAME}/runtime/protocol.py"
SERVER = f"{PKG_NAME}/runtime/server.py"
CLIENT = f"{PKG_NAME}/runtime/client.py"
SMI = f"{PKG_NAME}/tools/vtpu_smi.py"
METRICSD_INIT = f"{PKG_NAME}/metricsd/__init__.py"
METRICSD_SERVER = f"{PKG_NAME}/metricsd/server.py"
METRICS_GRPC = f"{PKG_NAME}/proto/tpu_metrics_grpc.py"


def parse_protocol(src: str, path: str = PROTOCOL
                   ) -> Tuple[Dict[str, int], Dict[str, Set[str]],
                              List[Finding]]:
    """(verb constants {NAME: line}, registries {REGISTRY: {NAME}},
    findings)."""
    findings: List[Finding] = []
    verbs: Dict[str, int] = {}
    registries: Dict[str, Set[str]] = {}
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return {}, {}, [Finding("verbs", path, e.lineno or 1,
                                f"syntax error: {e.msg}")]
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not isinstance(tgt, ast.Name) or not tgt.id.isupper():
            continue
        val = node.value
        if isinstance(val, ast.Constant) and isinstance(val.value, str):
            verbs[tgt.id] = node.lineno
        elif isinstance(val, (ast.Tuple, ast.List)) and \
                tgt.id.endswith("_VERBS"):
            names = set()
            for el in val.elts:
                if isinstance(el, ast.Name):
                    names.add(el.id)
                else:
                    findings.append(Finding(
                        "verbs", path, el.lineno,
                        f"{tgt.id} entry is not a verb constant name"))
            registries[tgt.id] = names
    for reg in ("TENANT_VERBS", "ADMIN_VERBS", "BIND_FREE_VERBS"):
        if reg not in registries:
            findings.append(Finding(
                "verbs", path, 1,
                f"protocol registry {reg} is missing"))
            registries[reg] = set()
    known = registries["TENANT_VERBS"] | registries["ADMIN_VERBS"]
    for name, line in verbs.items():
        if name not in known:
            findings.append(Finding(
                "verbs", path, line,
                f"verb {name} is in neither TENANT_VERBS nor "
                f"ADMIN_VERBS"))
    for reg, names in registries.items():
        for name in names:
            if name not in verbs:
                findings.append(Finding(
                    "verbs", path, 1,
                    f"{reg} names unknown verb constant {name}"))
    for name in registries["BIND_FREE_VERBS"]:
        for reg in ("TENANT_VERBS", "ADMIN_VERBS"):
            if name in verbs and name not in registries[reg]:
                findings.append(Finding(
                    "verbs", path, verbs.get(name, 1),
                    f"bind-free verb {name} must be served on both "
                    f"sockets but is missing from {reg}"))
    return verbs, registries, findings


def _find_func(tree: ast.AST, cls: str, fn: str
               ) -> Optional[ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == cls:
            for sub in node.body:
                if isinstance(sub, ast.FunctionDef) and sub.name == fn:
                    return sub
    return None


def dispatch_arms(fn: ast.FunctionDef) -> Dict[str, int]:
    """{verb constant name: first line} for every ``kind == P.X`` /
    ``kind in (P.X, ...)`` comparison in the handler."""
    arms: Dict[str, int] = {}
    for node in ast.walk(fn):
        if not isinstance(node, ast.Compare):
            continue
        involved = [node.left] + list(node.comparators)
        names = []
        for part in involved:
            if isinstance(part, ast.Attribute) and \
                    isinstance(part.value, ast.Name) and \
                    part.value.id == "P":
                names.append(part.attr)
            elif isinstance(part, (ast.Tuple, ast.List)):
                for el in part.elts:
                    if isinstance(el, ast.Attribute) and \
                            isinstance(el.value, ast.Name) and \
                            el.value.id == "P":
                        names.append(el.attr)
        for name in names:
            arms.setdefault(name, node.lineno)
    return arms


def no_hello_line(fn: ast.FunctionDef) -> Optional[int]:
    """Line of the ``NO_HELLO`` bind guard in _serve."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Constant) and node.value == "NO_HELLO":
            return node.lineno
    return None


def protocol_attr_refs(src: str) -> Set[str]:
    """Every ``P.<attr>`` attribute reference in a module — used to
    prove the client DERIVES its retry set from the protocol's
    idempotency registry instead of hand-maintaining a literal."""
    out: Set[str] = set()
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return out
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "P":
            out.add(node.attr)
    return out


def sender_bindings(src: str) -> Set[str]:
    """Verb constants sent by a module: dict literals carrying
    ``"kind": P.X``."""
    out: Set[str] = set()
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return out
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        for k, v in zip(node.keys, node.values):
            if isinstance(k, ast.Constant) and k.value == "kind" and \
                    isinstance(v, ast.Attribute) and \
                    isinstance(v.value, ast.Name) and v.value.id == "P":
                out.add(v.attr)
    return out


def check_texts(protocol_src: str, server_src: str, client_src: str,
                smi_src: str) -> List[Finding]:
    verbs, registries, findings = parse_protocol(protocol_src)
    if not verbs:
        return findings
    try:
        server_tree = ast.parse(server_src)
    except SyntaxError as e:
        return findings + [Finding("verbs", SERVER, e.lineno or 1,
                                   f"syntax error: {e.msg}")]
    serve = _find_func(server_tree, "TenantSession", "_serve")
    admin = _find_func(server_tree, "AdminSession", "handle")
    if serve is None or admin is None:
        return findings + [Finding(
            "verbs", SERVER, 1,
            "cannot locate TenantSession._serve / AdminSession.handle")]
    tenant_arms = dispatch_arms(serve)
    admin_arms = dispatch_arms(admin)
    for name in sorted(registries["TENANT_VERBS"]):
        if name not in tenant_arms:
            findings.append(Finding(
                "verbs", SERVER, serve.lineno,
                f"tenant verb {name} has no dispatch arm in "
                f"TenantSession._serve"))
    for name in sorted(registries["ADMIN_VERBS"]):
        if name not in admin_arms:
            findings.append(Finding(
                "verbs", SERVER, admin.lineno,
                f"admin verb {name} has no dispatch arm in "
                f"AdminSession.handle"))
    guard = no_hello_line(serve)
    if guard is None:
        findings.append(Finding(
            "verbs", SERVER, serve.lineno,
            "cannot locate the NO_HELLO bind guard in _serve"))
    else:
        for name in sorted(registries["BIND_FREE_VERBS"]):
            line = tenant_arms.get(name)
            if line is not None and line > guard:
                findings.append(Finding(
                    "verbs", SERVER, line,
                    f"bind-free verb {name} is dispatched AFTER the "
                    f"NO_HELLO guard (line {guard}) — an unbound probe "
                    f"would be refused"))
    client_sends = sender_bindings(client_src)
    for name in sorted(registries["TENANT_VERBS"]):
        if name not in client_sends:
            findings.append(Finding(
                "verbs", CLIENT, 1,
                f"tenant verb {name} has no client binding in "
                f"runtime/client.py"))
    smi_sends = sender_bindings(smi_src)
    for name in sorted(registries["ADMIN_VERBS"]):
        # STATS/TRACE ride the main socket from vtpu-smi too; any P.X
        # dict in the module counts as the operator binding.
        if name not in smi_sends:
            findings.append(Finding(
                "verbs", SMI, 1,
                f"admin verb {name} has no vtpu-smi binding"))
    findings.extend(_check_retry_safety(registries, client_src,
                                        verbs))
    return findings


# Verbs that can NEVER be classified idempotent: re-running an EXECUTE/
# EXEC_BATCH double-executes, a re-sent PUT_PART stages its chunk
# twice, SHUTDOWN/HANDOVER are one-shot lifecycle transitions.  The
# retry-safety checker holds the registry to this floor so a refactor
# cannot quietly make the client re-run device work.
MUTATING_VERBS = frozenset({"EXECUTE", "EXEC_BATCH", "PUT_PART",
                            "SHUTDOWN", "HANDOVER"})


def _check_retry_safety(registries: Dict[str, Set[str]],
                        client_src: str,
                        verbs: Dict[str, int]) -> List[Finding]:
    """Idempotency-classification exhaustiveness (docs/CHAOS.md): every
    served verb classified exactly once, mutating verbs never marked
    idempotent, and the client's transparent-retry set derived from
    the registry."""
    findings: List[Finding] = []
    idem = registries.get("IDEMPOTENT_VERBS")
    nonidem = registries.get("NONIDEMPOTENT_VERBS")
    if idem is None or nonidem is None:
        for reg in ("IDEMPOTENT_VERBS", "NONIDEMPOTENT_VERBS"):
            if registries.get(reg) is None:
                findings.append(Finding(
                    "verbs", PROTOCOL, 1,
                    f"retry-safety registry {reg} is missing — every "
                    f"verb must be classified for the client's "
                    f"transparent-retry contract"))
        return findings
    served = registries.get("TENANT_VERBS", set()) \
        | registries.get("ADMIN_VERBS", set())
    for name in sorted(served - idem - nonidem):
        findings.append(Finding(
            "verbs", PROTOCOL, verbs.get(name, 1),
            f"verb {name} is served but unclassified — add it to "
            f"IDEMPOTENT_VERBS or NONIDEMPOTENT_VERBS"))
    for name in sorted(idem & nonidem):
        findings.append(Finding(
            "verbs", PROTOCOL, verbs.get(name, 1),
            f"verb {name} is classified BOTH idempotent and "
            f"non-idempotent"))
    for name in sorted((idem | nonidem) - served):
        findings.append(Finding(
            "verbs", PROTOCOL, verbs.get(name, 1),
            f"verb {name} is retry-classified but served by neither "
            f"socket (dead classification)"))
    for name in sorted(MUTATING_VERBS & idem):
        findings.append(Finding(
            "verbs", PROTOCOL, verbs.get(name, 1),
            f"mutating verb {name} is marked idempotent — a "
            f"transparent retry would re-run device work"))
    if "IDEMPOTENT_VERBS" not in protocol_attr_refs(client_src):
        findings.append(Finding(
            "verbs", CLIENT, 1,
            "runtime/client.py does not reference "
            "P.IDEMPOTENT_VERBS — the transparent-retry set must be "
            "DERIVED from the registry, not hand-maintained"))
    return findings


def parse_metricsd_registry(src: str, path: str = METRICSD_INIT
                            ) -> Tuple[Set[str], List[Finding]]:
    """METRICSD_RPCS string-literal tuple from metricsd/__init__.py."""
    findings: List[Finding] = []
    rpcs: Set[str] = set()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return set(), [Finding("verbs", path, e.lineno or 1,
                               f"syntax error: {e.msg}")]
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "METRICSD_RPCS" and \
                isinstance(node.value, (ast.Tuple, ast.List)):
            for el in node.value.elts:
                if isinstance(el, ast.Constant) and \
                        isinstance(el.value, str):
                    rpcs.add(el.value)
                else:
                    findings.append(Finding(
                        "verbs", path, el.lineno,
                        "METRICSD_RPCS entry is not a string literal"))
    if not rpcs and not findings:
        findings.append(Finding(
            "verbs", path, 1,
            "metricsd/__init__.py has no METRICSD_RPCS registry"))
    return rpcs, findings


def _class_methods(tree: ast.AST, cls: str) -> Set[str]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == cls:
            return {sub.name for sub in node.body
                    if isinstance(sub, ast.FunctionDef)}
    return set()


def _stub_bindings(tree: ast.AST, cls: str) -> Set[str]:
    """``self.X = channel.…`` assignments in a stub class __init__."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.ClassDef) and node.name == cls):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 and \
                    isinstance(sub.targets[0], ast.Attribute) and \
                    isinstance(sub.targets[0].value, ast.Name) and \
                    sub.targets[0].value.id == "self":
                out.add(sub.targets[0].attr)
    return out


def _handler_keys(tree: ast.AST, fn_name: str) -> Set[str]:
    """String keys of the ``handlers = {...}`` dict in a registration
    helper."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.FunctionDef) and
                node.name == fn_name):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Dict):
                for k in sub.keys:
                    if isinstance(k, ast.Constant) and \
                            isinstance(k.value, str):
                        out.add(k.value)
    return out


def check_metricsd_texts(init_src: str, glue_src: str,
                         impl_src: str) -> List[Finding]:
    rpcs, findings = parse_metricsd_registry(init_src)
    if not rpcs:
        return findings
    try:
        glue_tree = ast.parse(glue_src)
    except SyntaxError as e:
        return findings + [Finding("verbs", METRICS_GRPC, e.lineno or 1,
                                   f"syntax error: {e.msg}")]
    try:
        impl_tree = ast.parse(impl_src)
    except SyntaxError as e:
        return findings + [Finding("verbs", METRICSD_SERVER,
                                   e.lineno or 1,
                                   f"syntax error: {e.msg}")]
    stub = _stub_bindings(glue_tree, "RuntimeMetricServiceStub")
    glue_servicer = _class_methods(glue_tree, "RuntimeMetricServiceServicer")
    handlers = _handler_keys(
        glue_tree, "add_RuntimeMetricServiceServicer_to_server")
    impl = _class_methods(impl_tree, "MetricsdServicer")
    for rpc in sorted(rpcs):
        if rpc not in stub:
            findings.append(Finding(
                "verbs", METRICS_GRPC, 1,
                f"metricsd RPC {rpc} has no RuntimeMetricServiceStub "
                f"binding"))
        if rpc not in glue_servicer:
            findings.append(Finding(
                "verbs", METRICS_GRPC, 1,
                f"metricsd RPC {rpc} has no RuntimeMetricServiceServicer "
                f"method"))
        if rpc not in handlers:
            findings.append(Finding(
                "verbs", METRICS_GRPC, 1,
                f"metricsd RPC {rpc} is missing from the "
                f"add_RuntimeMetricServiceServicer_to_server handlers"))
        if rpc not in impl:
            findings.append(Finding(
                "verbs", METRICSD_SERVER, 1,
                f"metricsd RPC {rpc} has no MetricsdServicer "
                f"implementation"))
    # Reverse direction: a CamelCase method on the implementation that
    # the registry does not know is an unregistered wire surface.
    for name in sorted(impl):
        if name[:1].isupper() and name not in rpcs:
            findings.append(Finding(
                "verbs", METRICSD_SERVER, 1,
                f"MetricsdServicer.{name} is implemented but not in "
                f"METRICSD_RPCS"))
    return findings


def check(root: str) -> List[Finding]:
    srcs = {rel: read_text(root, rel)
            for rel in (PROTOCOL, SERVER, CLIENT, SMI)}
    if any(v is None for v in srcs.values()):
        return []
    findings = check_texts(srcs[PROTOCOL], srcs[SERVER], srcs[CLIENT],
                           srcs[SMI])
    msrcs = {rel: read_text(root, rel)
             for rel in (METRICSD_INIT, METRICS_GRPC, METRICSD_SERVER)}
    if all(v is not None for v in msrcs.values()):
        findings.extend(check_metricsd_texts(
            msrcs[METRICSD_INIT], msrcs[METRICS_GRPC],
            msrcs[METRICSD_SERVER]))
    return findings
