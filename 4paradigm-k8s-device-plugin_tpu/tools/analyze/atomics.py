"""Shared-memory atomics checker — the static half of vtpu-wmm.

The mmap'd shared region (``native/vtpucore``) is cross-process state
mutated from C++ and mirrored into Python through ctypes; TSan only
catches the races a test schedule happens to hit, and nothing catches
a *memory-order* bug (a relaxed store where release was needed) on
x86 at all — it only detonates on arm64, in production.  So the
protocol is DECLARED, in a comment grammar inside ``vtpu_core.h``
(mirroring the lock-order docstring grammar of ``locks.py``), and this
checker proves the code matches the declaration:

  - every access to a declared shared-region struct field conforms to
    its category: ``mutex`` (the robust lock itself), ``lock`` (only
    under ``lock_region`` / in ``*_locked`` helpers / init paths),
    ``stable`` (written only by the flock-serialised ``init-writers``,
    plain reads allowed), ``crash-atomic`` (lock discipline PLUS the
    field must be one naturally-aligned machine word — the
    degraded-mode ledger reads it with the writer possibly dead
    mid-update), ``publish``/``seqlock`` (lock-free protocol fields:
    atomic builtins with the EXACT declared orders only);
  - publish/consume pairings hold in BOTH directions: a declared
    publish with no conforming store site, or no consume-side load, is
    a finding — as is any access at a different order;
  - the seqlock writer/reader functions follow the declared shape
    exactly (invalidate, release fence, payload helpers, release
    fence, release publish; acquire load, copy, acquire fence,
    re-check) — a dropped fence or re-check is a finding;
  - ``*_locked`` helpers are only CALLED from functions that hold the
    region lock;
  - implicit-order constructs are banned outright in the analyzed
    native sources: ``__sync_*`` builtins, ``volatile``,
    ``std::atomic`` operations without an explicit
    ``std::memory_order``, ``__ATOMIC_SEQ_CST`` on any declared field
    (seq_cst is never what these protocols mean — it must be declared
    if ever wanted);
  - struct layout agreement: the ctypes mirrors in ``shim/core.py``
    must match the C structs field-for-field (name, offset, size,
    total size), and the mirrored constants must agree — today that
    drift is a silent runtime corruption.

Beyond the original grammar, the vtpu-fastlane promotion added three
directive kinds (the exec ring's ``planned`` rows made live):
``rmw: <Struct.field> <order>`` fields admit ONLY read-modify-writes
at exactly the declared order (observability loads must be acquire,
plain stores are findings outside init); ``payload: <Struct.*>
<order>`` fields admit only atomics at the declared order; and
``ring <name>: tail=... headc=... credits=... helpers=... writer=...
reader=... completer=...`` shape-checks the real producer/consumer
functions — the writer must load the headc slot-reuse gate (acquire)
BEFORE filling the payload and publish the tail (release) after it, the
reader must consume the tail before copying, and the completer must
fill the completion payload before the headc release publish and
return the credit.  A skipped gate or a relaxed publish is a finding.

``planned`` declarations are still parsed and recorded but exempt
from code pairing: a future protocol's spec may lead its code.

Stdlib-only (re + ctypes for authoritative mirror offsets); tests
drive ``check_sources`` with seeded-violation fixture trees.
"""

from __future__ import annotations

import ast
import ctypes
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from . import Finding, read_text, PKG_NAME

HEADER = "native/vtpucore/vtpu_core.h"
NATIVE_ANALYZED = (
    "native/vtpucore/vtpu_core.h",
    "native/vtpucore/vtpu_core.cc",
    "native/vtpu_preload/preload.cc",
)
SHIM = f"{PKG_NAME}/shim/core.py"
ENVSPEC = f"{PKG_NAME}/utils/envspec.py"

GT_HEADER = "shared-memory protocol ground truth (vtpu-wmm)"

ORDERS = {
    "relaxed": "__ATOMIC_RELAXED",
    "acquire": "__ATOMIC_ACQUIRE",
    "release": "__ATOMIC_RELEASE",
    "acq_rel": "__ATOMIC_ACQ_REL",
    "seq_cst": "__ATOMIC_SEQ_CST",
}

# C scalar types the layout engine understands: name -> (size, align).
# Only LP64 scalars appear in the mirrored/shared structs; both x86-64
# and arm64 agree on these.
C_SCALARS = {
    "uint64_t": (8, 8), "int64_t": (8, 8),
    "uint32_t": (4, 4), "int32_t": (4, 4),
    "pid_t": (4, 4), "int": (4, 4), "unsigned": (4, 4),
}

CTYPES_SCALARS = {
    "c_uint64": ctypes.c_uint64, "c_int64": ctypes.c_int64,
    "c_uint32": ctypes.c_uint32, "c_int32": ctypes.c_int32,
    "c_int": ctypes.c_int, "c_uint": ctypes.c_uint,
}


# ---------------------------------------------------------------------------
# C source preprocessing
# ---------------------------------------------------------------------------

def strip_comments(src: str) -> str:
    """Blank out comments and string/char literals, preserving line
    structure (so line numbers survive and commented-out code or the
    word 'volatile' in prose never trips a ban)."""
    out: List[str] = []
    i, n = 0, len(src)
    mode = ""  # "" | "block" | "line" | '"' | "'"
    while i < n:
        c = src[i]
        nxt = src[i + 1] if i + 1 < n else ""
        if mode == "":
            if c == "/" and nxt == "*":
                mode = "block"
                out.append("  ")
                i += 2
                continue
            if c == "/" and nxt == "/":
                mode = "line"
                out.append("  ")
                i += 2
                continue
            if c in "\"'":
                mode = c
                out.append(c)
                i += 1
                continue
            out.append(c)
        elif mode == "block":
            if c == "*" and nxt == "/":
                mode = ""
                out.append("  ")
                i += 2
                continue
            out.append("\n" if c == "\n" else " ")
        elif mode == "line":
            if c == "\n":
                mode = ""
                out.append("\n")
            else:
                out.append(" ")
        else:  # string/char literal
            if c == "\\":
                out.append("  ")
                i += 2
                continue
            if c == mode:
                mode = ""
                out.append(c)
            else:
                out.append("\n" if c == "\n" else " ")
        i += 1
    return "".join(out)


# ---------------------------------------------------------------------------
# Ground-truth grammar
# ---------------------------------------------------------------------------

@dataclass
class SeqlockDecl:
    name: str
    seq: str = ""                      # Struct.field
    payload: List[str] = field(default_factory=list)
    helpers: Dict[str, str] = field(default_factory=dict)  # fn -> order
    writer: str = ""
    reader: str = ""


@dataclass
class RingDecl:
    """One ``ring <name>:`` declaration — the SPSC execute-ring shape
    (vtpu-fastlane): named protocol fields, payload helpers and the
    writer/reader/completer functions to shape-check."""

    name: str
    tail: str = ""        # Struct.field
    headc: str = ""
    credits: str = ""
    helpers: Dict[str, str] = field(default_factory=dict)  # fn -> order
    writer: str = ""
    reader: str = ""
    completer: str = ""


@dataclass
class GroundTruth:
    structs: List[str] = field(default_factory=list)
    # category per Struct.field ("mutex"|"lock"|"stable"|"crash-atomic"
    # |"publish"|"seq"|"payload"|"rmw"); wildcards expanded later.
    raw: Dict[str, List[str]] = field(default_factory=dict)
    publishes: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    seqlocks: List[SeqlockDecl] = field(default_factory=list)
    rmws: Dict[str, str] = field(default_factory=dict)      # spec -> order
    payloads: Dict[str, str] = field(default_factory=dict)  # spec -> order
    rings: List[RingDecl] = field(default_factory=list)
    init_writers: Set[str] = field(default_factory=set)
    locked_suffix: str = "_locked"
    mirrors: List[Tuple[str, str, str]] = field(default_factory=list)
    consts: List[Tuple[str, str, str]] = field(default_factory=list)
    planned: Dict[str, List[str]] = field(default_factory=dict)


_DIRECTIVE_RE = re.compile(
    r"^\s{1,4}(structs|mutex|lock|stable|crash-atomic|init-writers|"
    r"locked-suffix|publish|rmw|payload|seqlock\s+[\w-]+|"
    r"ring\s+[\w-]+|mirror|mirror-const|"
    r"planned\s+[\w-]+):\s*(.*)$")
_ORDERED_FIELD_RE = re.compile(r"^(\S+)\s+(\w+)\s*$")
_PUBLISH_RE = re.compile(
    r"^(\S+)\s+(\w+)\s*->\s*consume:\s*(\w+)\s*$")
_MIRROR_RE = re.compile(r"^(\S+)\s*==\s*(\S+?):(\w+)\s*$")


def parse_ground_truth(header_src: str, path: str = HEADER
                       ) -> Tuple[Optional[GroundTruth], List[Finding]]:
    findings: List[Finding] = []
    lines = header_src.splitlines()
    start = next((i for i, ln in enumerate(lines) if GT_HEADER in ln),
                 None)
    if start is None:
        return None, [Finding(
            "atomics", path, 1,
            f"vtpu_core.h has no `{GT_HEADER}` block — the shared-"
            f"memory protocol must be declared")]
    gt = GroundTruth()
    # (directive key, value text, line) accumulated with continuations
    entries: List[Tuple[str, str, int]] = []
    for off, raw_line in enumerate(lines[start + 1:], start + 2):
        if "*/" in raw_line:
            break
        body = re.sub(r"^\s*\*", "", raw_line)
        body = body[1:] if body.startswith(" ") else body
        m = _DIRECTIVE_RE.match(body)
        if m:
            entries.append((m.group(1), m.group(2).strip(), off))
        elif entries and re.match(r"^\s{5,}\S", body):
            key, val, ln = entries[-1]
            entries[-1] = (key, f"{val} {body.strip()}", ln)
    for key, val, ln in entries:
        if key == "structs":
            gt.structs = [t.strip() for t in val.split(",") if t.strip()]
        elif key in ("mutex", "lock", "stable", "crash-atomic"):
            gt.raw.setdefault(key, []).extend(
                t.strip() for t in val.split(",") if t.strip())
        elif key == "init-writers":
            gt.init_writers.update(
                t.strip() for t in val.split(",") if t.strip())
        elif key == "locked-suffix":
            gt.locked_suffix = val.strip()
        elif key == "rmw":
            m = _ORDERED_FIELD_RE.match(val)
            if not m or m.group(2) not in ORDERS:
                findings.append(Finding(
                    "atomics", path, ln,
                    f"malformed rmw declaration: {val!r} (want "
                    f"`<Struct.field> <order>`)"))
                continue
            gt.rmws[m.group(1)] = m.group(2)
        elif key == "payload":
            m = _ORDERED_FIELD_RE.match(val)
            if not m or m.group(2) not in ORDERS:
                findings.append(Finding(
                    "atomics", path, ln,
                    f"malformed payload declaration: {val!r} (want "
                    f"`<Struct.field|Struct.*> <order>`)"))
                continue
            gt.payloads[m.group(1)] = m.group(2)
        elif key.startswith("ring"):
            decl = RingDecl(name=key.split(None, 1)[1])
            for tok in re.finditer(r"(\w+)=([^=]+?)(?=\s+\w+=|$)", val):
                k, v = tok.group(1), tok.group(2).strip()
                if k in ("tail", "headc", "credits"):
                    setattr(decl, k, v)
                elif k == "helpers":
                    for h in re.finditer(r"(\w+)\((\w+)\)", v):
                        if h.group(2) not in ORDERS:
                            findings.append(Finding(
                                "atomics", path, ln,
                                f"ring {decl.name}: helper "
                                f"{h.group(1)} has unknown order "
                                f"{h.group(2)!r}"))
                        decl.helpers[h.group(1)] = h.group(2)
                elif k in ("writer", "reader", "completer"):
                    setattr(decl, k, v.split()[0])
            if not (decl.tail and decl.headc and decl.credits
                    and decl.helpers and decl.writer and decl.reader
                    and decl.completer):
                findings.append(Finding(
                    "atomics", path, ln,
                    f"ring {decl.name}: incomplete declaration (need "
                    f"tail=, headc=, credits=, helpers=, writer=, "
                    f"reader=, completer=)"))
            gt.rings.append(decl)
        elif key == "publish":
            m = _PUBLISH_RE.match(val)
            if not m:
                findings.append(Finding(
                    "atomics", path, ln,
                    f"malformed publish declaration: {val!r} (want "
                    f"`<Struct.field> <order> -> consume: <order>`)"))
                continue
            fld, sord, lord = m.groups()
            if sord not in ORDERS or lord not in ORDERS:
                findings.append(Finding(
                    "atomics", path, ln,
                    f"publish {fld}: unknown order "
                    f"{sord!r}/{lord!r} (know {sorted(ORDERS)})"))
                continue
            gt.publishes[fld] = (sord, lord)
        elif key.startswith("seqlock"):
            decl = SeqlockDecl(name=key.split(None, 1)[1])
            for tok in re.finditer(r"(\w+)=([^=]+?)(?=\s+\w+=|$)", val):
                k, v = tok.group(1), tok.group(2).strip()
                if k == "seq":
                    decl.seq = v
                elif k == "payload":
                    decl.payload = [t.strip() for t in v.split(",")
                                    if t.strip()]
                elif k == "helpers":
                    for h in re.finditer(r"(\w+)\((\w+)\)", v):
                        if h.group(2) not in ORDERS:
                            findings.append(Finding(
                                "atomics", path, ln,
                                f"seqlock {decl.name}: helper "
                                f"{h.group(1)} has unknown order "
                                f"{h.group(2)!r}"))
                        decl.helpers[h.group(1)] = h.group(2)
                elif k == "writer":
                    decl.writer = v.split()[0]
                elif k == "reader":
                    decl.reader = v.split()[0]
            if not (decl.seq and decl.payload and decl.helpers
                    and decl.writer and decl.reader):
                findings.append(Finding(
                    "atomics", path, ln,
                    f"seqlock {decl.name}: incomplete declaration "
                    f"(need seq=, payload=, helpers=, writer=, "
                    f"reader=)"))
            gt.seqlocks.append(decl)
        elif key == "mirror":
            m = _MIRROR_RE.match(val)
            if not m:
                findings.append(Finding(
                    "atomics", path, ln,
                    f"malformed mirror declaration: {val!r} (want "
                    f"`<c_struct> == <pyfile>:<PyClass>`)"))
                continue
            gt.mirrors.append(m.groups())
        elif key == "mirror-const":
            m = _MIRROR_RE.match(val)
            if not m:
                findings.append(Finding(
                    "atomics", path, ln,
                    f"malformed mirror-const declaration: {val!r}"))
                continue
            gt.consts.append(m.groups())
        elif key.startswith("planned"):
            gt.planned.setdefault(key.split(None, 1)[1], []).append(val)
    if not gt.structs:
        findings.append(Finding(
            "atomics", path, start + 1,
            "ground-truth block declares no `structs:` list"))
    return gt, findings


# ---------------------------------------------------------------------------
# C struct parsing + layout
# ---------------------------------------------------------------------------

@dataclass
class CField:
    name: str
    ctype: str
    array: Optional[int]   # None = scalar, 0 = flexible array


_STRUCT_RE = re.compile(
    r"typedef\s+struct(?:\s+\w+)?\s*\{(.*?)\}\s*(\w+)\s*;", re.S)
_DEFINE_RE = re.compile(r"#define\s+(\w+)\s+(\d+)\b")
_MEMBER_RE = re.compile(
    r"^(\w[\w\s]*?)\s+(\w+)\s*(?:\[\s*(\w*)\s*\])?$")


def parse_c_structs(stripped_sources: Dict[str, str]
                    ) -> Tuple[Dict[str, List[CField]], Dict[str, int]]:
    defines: Dict[str, int] = {}
    structs: Dict[str, List[CField]] = {}
    for src in stripped_sources.values():
        for m in _DEFINE_RE.finditer(src):
            defines.setdefault(m.group(1), int(m.group(2)))
    for src in stripped_sources.values():
        for m in _STRUCT_RE.finditer(src):
            body, name = m.group(1), m.group(2)
            fields: List[CField] = []
            for stmt in body.split(";"):
                stmt = " ".join(stmt.split())
                if not stmt:
                    continue
                mm = _MEMBER_RE.match(stmt)
                if not mm:
                    continue
                ctype = " ".join(mm.group(1).split())
                arr = mm.group(3)
                if arr is None:
                    array: Optional[int] = None
                elif arr == "":
                    array = 0
                elif arr.isdigit():
                    array = int(arr)
                else:
                    array = defines.get(arr, -1)
                fields.append(CField(mm.group(2), ctype, array))
            structs[name] = fields
    return structs, defines


def c_layout(name: str, structs: Dict[str, List[CField]]
             ) -> Optional[List[Tuple[str, int, int]]]:
    """[(field, offset, size)] under natural LP64 alignment, or None
    when the struct holds a type the engine cannot size (the robust
    mutex — layouts are only needed for mirrored/plain-scalar
    structs)."""
    fields = structs.get(name)
    if fields is None:
        return None
    out: List[Tuple[str, int, int]] = []
    off = 0
    maxal = 1
    for f in fields:
        if f.ctype in C_SCALARS:
            size, align = C_SCALARS[f.ctype]
        elif f.ctype in structs:
            sub = c_layout(f.ctype, structs)
            if sub is None:
                return None
            size = _c_size(f.ctype, structs)
            align = max((s for _n, _o, s in sub if s in (1, 2, 4, 8)),
                        default=8)
        else:
            return None
        count = 1 if f.array is None else f.array
        if count < 0:
            return None
        off = (off + align - 1) // align * align
        out.append((f.name, off, size * count))
        off += size * count
        maxal = max(maxal, align)
    return out


def _c_size(name: str, structs: Dict[str, List[CField]]) -> int:
    lay = c_layout(name, structs)
    if not lay:
        return 0
    end = max(o + s for _n, o, s in lay)
    al = max((s for f in structs[name]
              for s in [C_SCALARS.get(f.ctype, (0, 1))[1]]), default=1)
    al = max(al, 1)
    return (end + al - 1) // al * al


# ---------------------------------------------------------------------------
# ctypes mirror parsing (shim/core.py, by AST — never imported)
# ---------------------------------------------------------------------------

def parse_ctypes_structs(shim_src: str, const_sources: Dict[str, str]
                         ) -> Tuple[Dict[str, List[Tuple[str, str,
                                                         Optional[int]]]],
                                    Dict[str, int]]:
    """{PyClass: [(field, ctype_name, arraylen)]} plus the integer
    module constants of shim/envspec (for array lengths and
    mirror-const)."""
    consts: Dict[str, int] = {}
    for src in const_sources.values():
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, int):
                consts.setdefault(node.targets[0].id, node.value.value)
    structs: Dict[str, List[Tuple[str, str, Optional[int]]]] = {}
    try:
        tree = ast.parse(shim_src)
    except SyntaxError:
        return structs, consts
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for stmt in node.body:
            if not (isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == "_fields_"
                    and isinstance(stmt.value, (ast.List, ast.Tuple))):
                continue
            fields: List[Tuple[str, str, Optional[int]]] = []
            for el in stmt.value.elts:
                if not (isinstance(el, ast.Tuple) and len(el.elts) == 2
                        and isinstance(el.elts[0], ast.Constant)):
                    continue
                fname = el.elts[0].value
                t = el.elts[1]
                arraylen: Optional[int] = None
                if isinstance(t, ast.BinOp) and isinstance(t.op, ast.Mult):
                    base, n = t.left, t.right
                    if isinstance(n, ast.Name):
                        arraylen = consts.get(n.id, -1)
                    elif isinstance(n, ast.Constant):
                        arraylen = n.value
                    t = base
                cname = t.attr if isinstance(t, ast.Attribute) else (
                    t.id if isinstance(t, ast.Name) else "?")
                fields.append((fname, cname, arraylen))
            structs[node.name] = fields
    return structs, consts


def ctypes_layout(fields: List[Tuple[str, str, Optional[int]]]
                  ) -> Optional[List[Tuple[str, int, int]]]:
    """Authoritative offsets/sizes straight from a dynamically-built
    ctypes.Structure — the exact layout the shim runs with."""
    spec = []
    for fname, cname, arraylen in fields:
        base = CTYPES_SCALARS.get(cname)
        if base is None or (arraylen is not None and arraylen < 0):
            return None
        spec.append((fname, base * arraylen if arraylen else base))
    try:
        T = type("_AtomicsMirror", (ctypes.Structure,),
                 {"_fields_": spec})
    except (TypeError, ValueError):
        return None
    return [(fname, getattr(T, fname).offset, getattr(T, fname).size)
            for fname, _t in spec]


# ---------------------------------------------------------------------------
# Function extraction + statement model
# ---------------------------------------------------------------------------

@dataclass
class CFunc:
    name: str
    path: str
    line: int
    statements: List[Tuple[int, str]]   # (line, text)
    locked: bool = False


def split_functions(stripped: str, path: str) -> List[CFunc]:
    funcs: List[CFunc] = []
    depth = 0
    i, n = 0, len(stripped)
    line = 1
    body_start = None
    fn_name = ""
    fn_line = 0
    body_depth = 0
    while i < n:
        c = stripped[i]
        if c == "\n":
            line += 1
        elif c == "{":
            if depth == 0 or (body_start is None and depth > 0):
                # Function body iff the brace follows a ')'.
                j = i - 1
                while j >= 0 and stripped[j] in " \t\n":
                    j -= 1
                if j >= 0 and stripped[j] == ")" and body_start is None \
                        and depth == 0:
                    # walk back over the balanced parens to the name
                    bal = 0
                    k = j
                    while k >= 0:
                        if stripped[k] == ")":
                            bal += 1
                        elif stripped[k] == "(":
                            bal -= 1
                            if bal == 0:
                                break
                        k -= 1
                    m = re.search(r"(\w+)\s*$", stripped[:max(k, 0)])
                    if m:
                        fn_name = m.group(1)
                        fn_line = line
                        body_start = i + 1
                        body_depth = depth
            depth += 1
        elif c == "}":
            depth -= 1
            if body_start is not None and depth == body_depth:
                body = stripped[body_start:i]
                start_line = stripped[:body_start].count("\n") + 1
                funcs.append(CFunc(fn_name, path, fn_line,
                                   _statements(body, start_line)))
                body_start = None
        i += 1
    return funcs


def _statements(body: str, start_line: int) -> List[Tuple[int, str]]:
    out: List[Tuple[int, str]] = []
    cur: List[str] = []
    line = start_line
    cur_line = line
    for c in body:
        if c == "\n":
            line += 1
        if c in ";{}":
            text = " ".join("".join(cur).split())
            if text:
                out.append((cur_line, text))
            cur = []
            cur_line = line
        else:
            if not cur and not c.isspace():
                cur_line = line
            cur.append(c)
    text = " ".join("".join(cur).split())
    if text:
        out.append((cur_line, text))
    return out


_CHAIN_RE = re.compile(
    r"\b\w+(?:\s*(?:->|\.)\s*\w+|\s*\[[^][]*\])+")
_ATOMIC_OP_RE = re.compile(r"__atomic_(\w+)")
_ATOMIC_ORDER_RE = re.compile(r"__ATOMIC_([A-Z_]+)")
_WRITE_AFTER_RE = re.compile(
    r"^\s*(=(?!=)|\+=|-=|\|=|&=|\^=|\+\+|--)")


def chain_fields(stmt: str, known: Set[str]) -> List[Tuple[str, bool]]:
    """Declared-field accesses in one statement: [(field, is_write)].
    Only pointer-rooted chains count — a chain with no ``->`` is a
    stack local (e.g. the writer's temporary vtpu_trace_event)."""
    out: List[Tuple[str, bool]] = []
    for m in _CHAIN_RE.finditer(stmt):
        chain = m.group(0)
        if "->" not in chain:
            continue
        tail = stmt[m.end():]
        is_write = bool(_WRITE_AFTER_RE.match(tail))
        accessed = re.findall(r"(?:->|\.)\s*(\w+)", chain)
        for idx, name in enumerate(accessed):
            if name in known:
                last = idx == len(accessed) - 1
                out.append((name, is_write and last))
    return out


# ---------------------------------------------------------------------------
# The checker
# ---------------------------------------------------------------------------

class _Checker:
    def __init__(self, gt: GroundTruth,
                 structs: Dict[str, List[CField]]) -> None:
        self.gt = gt
        self.structs = structs
        self.findings: List[Finding] = []
        # field name -> set of categories (same name may exist in
        # several structs; an access is fine if ANY category allows it
        # — one-sided: misses possible, false positives not)
        self.cats: Dict[str, Set[str]] = {}
        self.publish_by_field: Dict[str, Tuple[str, str]] = {}
        self.seq_fields: Set[str] = set()
        self.helper_names: Dict[str, str] = {}
        # declared orders for rmw/payload fields (bare field name)
        self.rmw_by_field: Dict[str, str] = {}
        self.payload_by_field: Dict[str, str] = {}
        # pairing evidence: field -> {"store": [...], "load": [...],
        # "rmw": [...]}
        self.sites: Dict[str, Dict[str, List[str]]] = {}

    def finding(self, path: str, line: int, msg: str) -> None:
        self.findings.append(Finding("atomics", path, line, msg))

    # -- category table ----------------------------------------------------

    def build_categories(self, path: str) -> None:
        gt = self.gt
        declared_fields: Dict[str, str] = {}

        def add(spec: str, cat: str, override_ok: bool = False) -> None:
            if "." not in spec:
                self.finding(path, 1,
                             f"{cat} declaration {spec!r} is not "
                             f"`Struct.field`")
                return
            sname, fname = spec.split(".", 1)
            if sname not in gt.structs:
                self.finding(path, 1,
                             f"{cat} declares {spec!r} but {sname} is "
                             f"not in the `structs:` list")
                return
            fields = self.structs.get(sname)
            if fields is None:
                self.finding(path, 1,
                             f"declared struct {sname} not found in "
                             f"the native sources")
                return
            names = [f.name for f in fields] if fname == "*" else [fname]
            for nm in names:
                if fname != "*" and nm not in [f.name for f in fields]:
                    self.finding(path, 1,
                                 f"{cat} declares {sname}.{nm} but "
                                 f"{sname} has no such field")
                    continue
                key = f"{sname}.{nm}"
                prev = declared_fields.get(key)
                if prev and prev != cat and fname != "*" \
                        and not override_ok:
                    self.finding(path, 1,
                                 f"{key} declared both {prev} and "
                                 f"{cat}")
                declared_fields[key] = cat
                self.cats.setdefault(nm, set()).add(cat)

        for cat in ("mutex", "lock", "stable"):
            for spec in gt.raw.get(cat, ()):
                add(spec, cat)
        # crash-atomic refines lock (most-specific wins, no conflict)
        for spec in gt.raw.get("crash-atomic", ()):
            add(spec, "crash-atomic", override_ok=True)
        for fld, (sord, lord) in gt.publishes.items():
            add(fld, "publish")
            self.publish_by_field[fld.split(".", 1)[1]] = (sord, lord)
        for fld, order in gt.rmws.items():
            add(fld, "rmw")
            self.rmw_by_field[fld.split(".", 1)[1]] = order
        for fld, order in gt.payloads.items():
            add(fld, "payload")
            if "." in fld:
                sname, fname = fld.split(".", 1)
                names = ([f.name for f in self.structs.get(sname, ())]
                         if fname == "*" else [fname])
                for nm in names:
                    self.payload_by_field[nm] = order
        for rg in gt.rings:
            self.helper_names.update(rg.helpers)
        for sl in gt.seqlocks:
            if sl.seq:
                add(sl.seq, "seq")
                self.seq_fields.add(sl.seq.split(".", 1)[1])
            for p in sl.payload:
                add(p, "payload")
            self.helper_names.update(sl.helpers)
        # exhaustiveness: every field of every declared struct has a
        # category
        for sname in gt.structs:
            for f in self.structs.get(sname, ()):
                if f"{sname}.{f.name}" not in declared_fields:
                    self.finding(
                        path, 1,
                        f"{sname}.{f.name} is a shared-region field "
                        f"with NO declared access category — extend "
                        f"the vtpu_core.h ground-truth block")

    # -- per-function access discipline ------------------------------------

    def scan_function(self, fn: CFunc) -> None:
        gt = self.gt
        is_init = fn.name in gt.init_writers
        locked = fn.locked or is_init \
            or fn.name.endswith(gt.locked_suffix)
        known = set(self.cats)
        for line, stmt in fn.statements:
            has_atomic = "__atomic_" in stmt
            orders = _ATOMIC_ORDER_RE.findall(stmt)
            opm = _ATOMIC_OP_RE.search(stmt)
            op = opm.group(1) if opm else ""
            helper_called = next(
                (h for h in self.helper_names
                 if re.search(rf"\b{h}\s*\(", stmt)), None)
            # *_locked callees only from locked contexts
            for cm in re.finditer(
                    rf"\b(\w+{re.escape(gt.locked_suffix)})\s*\(",
                    stmt):
                if not locked:
                    self.finding(
                        fn.path, line,
                        f"{fn.name} calls {cm.group(1)} without "
                        f"holding the region lock (the "
                        f"`{gt.locked_suffix}` suffix is a held-lock "
                        f"contract)")
            for fname, is_write in chain_fields(stmt, known):
                cats = self.cats[fname]
                if has_atomic:
                    self._check_atomic(fn, line, stmt, fname, cats,
                                       op, orders)
                    continue
                if "mutex" in cats:
                    continue
                if helper_called and "payload" in cats:
                    continue
                if is_init:
                    continue
                if ("lock" in cats or "crash-atomic" in cats) and locked:
                    continue
                if "stable" in cats and not is_write:
                    continue
                if "stable" in cats and is_write:
                    self.finding(
                        fn.path, line,
                        f"{fn.name} writes stable field `{fname}` "
                        f"outside the declared init-writers "
                        f"({sorted(gt.init_writers)})")
                    continue
                if cats & {"publish", "seq", "payload", "rmw"}:
                    self.finding(
                        fn.path, line,
                        f"{fn.name}: plain access to lock-free "
                        f"protocol field `{fname}` — must go through "
                        f"a declared atomic helper with an explicit "
                        f"memory order")
                    continue
                self.finding(
                    fn.path, line,
                    f"{fn.name}: plain access to shared-region field "
                    f"`{fname}` outside the region lock (no "
                    f"lock_region in scope)")

    def _check_atomic(self, fn: CFunc, line: int, stmt: str,
                      fname: str, cats: Set[str], op: str,
                      orders: List[str]) -> None:
        if "SEQ_CST" in orders:
            self.finding(
                fn.path, line,
                f"{fn.name}: __ATOMIC_SEQ_CST on `{fname}` — seq_cst "
                f"is never declared for these protocols; declare the "
                f"order the protocol actually needs")
            return
        is_store = op.startswith("store")
        is_load = op.startswith("load")
        is_rmw = op.startswith(("fetch", "exchange", "compare", "add",
                                "sub", "and", "or", "xor"))
        order = orders[0] if orders else ""
        rec = self.sites.setdefault(fname, {"store": [], "load": [],
                                            "rmw": []})
        rec.setdefault("rmw", [])
        if is_store or is_rmw:
            rec["store"].append(order)
        if is_load or is_rmw:
            rec["load"].append(order)
        if is_rmw:
            rec["rmw"].append(order)
        if "rmw" in cats and fname in self.rmw_by_field:
            want = self.rmw_by_field[fname].upper()
            if is_rmw and order != want:
                self.finding(
                    fn.path, line,
                    f"{fn.name}: `{fname}` is a declared `rmw: ... "
                    f"{want.lower()}` field but this RMW runs at "
                    f"__ATOMIC_{order or '???'}")
            elif is_load and not is_rmw and order != "ACQUIRE":
                self.finding(
                    fn.path, line,
                    f"{fn.name}: observability load of rmw field "
                    f"`{fname}` must be __ATOMIC_ACQUIRE (got "
                    f"__ATOMIC_{order or '???'})")
            elif is_store and not is_rmw:
                self.finding(
                    fn.path, line,
                    f"{fn.name}: plain atomic STORE to rmw field "
                    f"`{fname}` — only read-modify-writes at the "
                    f"declared order may mutate it outside init")
            return
        if "payload" in cats and fname in self.payload_by_field:
            want = self.payload_by_field[fname].upper()
            if order != want:
                self.finding(
                    fn.path, line,
                    f"{fn.name}: payload field `{fname}` accessed at "
                    f"__ATOMIC_{order or '???'} but declared "
                    f"`payload: ... {want.lower()}`")
            return
        if "publish" in cats:
            want_store, want_load = self.publish_by_field[fname]
            if (is_store or is_rmw) and order != ORDERS[want_store] \
                    .replace("__ATOMIC_", ""):
                self.finding(
                    fn.path, line,
                    f"{fn.name}: `{fname}` published at __ATOMIC_"
                    f"{order or '???'} but declared "
                    f"`publish: ... {want_store}`")
            if is_load and not is_rmw and order != ORDERS[want_load] \
                    .replace("__ATOMIC_", ""):
                self.finding(
                    fn.path, line,
                    f"{fn.name}: `{fname}` consumed at __ATOMIC_"
                    f"{order or '???'} but declared "
                    f"`consume: {want_load}`")

    # -- publish/consume pairing (both directions) -------------------------

    def check_pairing(self, path: str) -> None:
        for fld, (sord, lord) in self.gt.publishes.items():
            fname = fld.split(".", 1)[1]
            rec = self.sites.get(fname, {"store": [], "load": []})
            if not rec["store"]:
                self.finding(
                    path, 1,
                    f"declared `publish: {fld} {sord}` has no "
                    f"conforming publish site in the native sources "
                    f"(pairing must hold in both directions)")
            if not rec["load"]:
                self.finding(
                    path, 1,
                    f"declared `publish: {fld}` has no consume-side "
                    f"load site (declared `consume: {lord}`)")
        for fld, order in self.gt.rmws.items():
            fname = fld.split(".", 1)[1]
            rec = self.sites.get(fname, {})
            if not rec.get("rmw"):
                self.finding(
                    path, 1,
                    f"declared `rmw: {fld} {order}` has no "
                    f"read-modify-write site in the native sources "
                    f"(pairing must hold in both directions)")

    # -- exec-ring shape (vtpu-fastlane) -----------------------------------

    def check_rings(self, funcs: Dict[str, CFunc]) -> None:
        """The SPSC execute-ring writer/reader/completer must follow
        the declared shape: the writer loads the headc slot-reuse gate
        (acquire) BEFORE the payload helper and publishes the tail
        after it; the reader consumes the tail before copying; the
        completer fills the completion payload before the headc
        release publish and returns the credit with an RMW.  A writer
        that skips the headc gate overwrites unconsumed descriptors —
        that is the seeded-violation class this check exists for."""
        for rg in self.gt.rings:
            if not (rg.tail and rg.headc and rg.credits and rg.writer
                    and rg.reader and rg.completer):
                continue
            tail_f = rg.tail.split(".", 1)[1]
            headc_f = rg.headc.split(".", 1)[1]
            credits_f = rg.credits.split(".", 1)[1]
            missing = [fn for fn in (rg.writer, rg.reader,
                                     rg.completer)
                       if fn not in funcs]
            if missing:
                self.findings.append(Finding(
                    "atomics", HEADER, 1,
                    f"ring {rg.name}: declared function(s) "
                    f"{missing} not found in the native sources"))
                continue

            def idx(evs, kind, fld=None, first=True):
                hits = [i for i, (k, f, _o) in enumerate(evs)
                        if k == kind and (fld is None or f == fld)]
                if not hits:
                    return None
                return hits[0] if first else hits[-1]

            w = funcs[rg.writer]
            evs = self._ring_events(w, tail_f, headc_f, credits_f,
                                    rg.helpers)
            helper_i = idx(evs, "helper")
            gate_i = idx(evs, "load", headc_f)
            pub_i = idx(evs, "store", tail_f, first=False)
            if helper_i is None:
                self.findings.append(Finding(
                    "atomics", w.path, w.line,
                    f"ring {rg.name}: writer {w.name} never fills the "
                    f"payload through a declared helper"))
            if gate_i is None or (helper_i is not None
                                  and gate_i > helper_i):
                self.findings.append(Finding(
                    "atomics", w.path, w.line,
                    f"ring {rg.name}: writer {w.name} SKIPS the "
                    f"`{headc_f}` slot-reuse gate (an acquire load "
                    f"before the payload fill) — a wrap can overwrite "
                    f"a descriptor the consumer has not republished"))
            if pub_i is None or (helper_i is not None
                                 and pub_i < helper_i):
                self.findings.append(Finding(
                    "atomics", w.path, w.line,
                    f"ring {rg.name}: writer {w.name} does not "
                    f"publish `{tail_f}` after the payload fill"))
            if idx(evs, "rmw", credits_f) is None:
                self.findings.append(Finding(
                    "atomics", w.path, w.line,
                    f"ring {rg.name}: writer {w.name} skips the "
                    f"`{credits_f}` admission gate RMW"))
            r = funcs[rg.reader]
            evs = self._ring_events(r, tail_f, headc_f, credits_f,
                                    rg.helpers)
            helper_i = idx(evs, "helper")
            tail_i = idx(evs, "load", tail_f)
            if helper_i is None or tail_i is None \
                    or tail_i > helper_i:
                self.findings.append(Finding(
                    "atomics", r.path, r.line,
                    f"ring {rg.name}: reader {r.name} must consume "
                    f"`{tail_f}` (acquire) before copying the payload "
                    f"through a declared helper"))
            c = funcs[rg.completer]
            evs = self._ring_events(c, tail_f, headc_f, credits_f,
                                    rg.helpers)
            helper_i = idx(evs, "helper")
            pub_i = idx(evs, "store", headc_f, first=False)
            if helper_i is None or pub_i is None \
                    or pub_i < helper_i:
                self.findings.append(Finding(
                    "atomics", c.path, c.line,
                    f"ring {rg.name}: completer {c.name} must fill "
                    f"the completion payload BEFORE publishing "
                    f"`{headc_f}` (the slot-reuse gate)"))
            if idx(evs, "rmw", credits_f) is None:
                self.findings.append(Finding(
                    "atomics", c.path, c.line,
                    f"ring {rg.name}: completer {c.name} never "
                    f"returns the `{credits_f}` admission credit"))

    def _ring_events(self, fn: CFunc, tail_f: str, headc_f: str,
                     credits_f: str, helpers: Dict[str, str]
                     ) -> List[Tuple[str, str, str]]:
        """(kind, field, order) events of one ring function: atomic
        ops on the three protocol fields, payload-helper calls and
        fences, in statement order."""
        events: List[Tuple[str, str, str]] = []
        for _line, stmt in fn.statements:
            if "__atomic_thread_fence" in stmt:
                m = _ATOMIC_ORDER_RE.search(stmt)
                events.append(("fence", "", m.group(1) if m else "?"))
                continue
            helper = next((h for h in helpers
                           if re.search(rf"\b{h}\s*\(", stmt)), None)
            if helper:
                events.append(("helper", helper, helpers[helper]))
                continue
            if "__atomic_" not in stmt:
                continue
            for fld in (tail_f, headc_f, credits_f):
                if not re.search(rf"(?:->|\.)\s*{fld}\b", stmt):
                    continue
                opm = _ATOMIC_OP_RE.search(stmt)
                om = _ATOMIC_ORDER_RE.search(stmt)
                op = opm.group(1) if opm else ""
                if op.startswith("store"):
                    kind = "store"
                elif op.startswith("load"):
                    kind = "load"
                else:
                    kind = "rmw"
                events.append((kind, fld, om.group(1) if om else "?"))
        return events

    # -- seqlock shape -----------------------------------------------------

    def check_seqlocks(self, funcs: Dict[str, CFunc]) -> None:
        for sl in self.gt.seqlocks:
            if not (sl.seq and sl.writer and sl.reader):
                continue
            seq_field = sl.seq.split(".", 1)[1]
            w = funcs.get(sl.writer)
            r = funcs.get(sl.reader)
            if w is None or r is None:
                self.findings.append(Finding(
                    "atomics", HEADER, 1,
                    f"seqlock {sl.name}: declared writer/reader "
                    f"{sl.writer}/{sl.reader} not found in the "
                    f"native sources"))
                continue
            self._match_shape(
                w, self._events(w, seq_field),
                [("store", "RELAXED"), ("fence", "RELEASE"),
                 ("helper", next(iter(sl.helpers))),
                 ("fence", "RELEASE"), ("store", "RELEASE")],
                sl.name, "writer: invalidate(relaxed), release "
                "fence, payload, release fence, publish(release)")
            helpers = list(sl.helpers)
            reader_helper = helpers[1] if len(helpers) > 1 else helpers[0]
            self._match_shape(
                r, self._events(r, seq_field),
                [("load", "ACQUIRE"), ("helper", reader_helper),
                 ("fence", "ACQUIRE"), ("load", "ACQUIRE")],
                sl.name, "reader: seq acquire, copy, acquire fence, "
                "seq re-check(acquire)")

    def _events(self, fn: CFunc, seq_field: str
                ) -> List[Tuple[str, str]]:
        events: List[Tuple[str, str]] = []
        for _line, stmt in fn.statements:
            if "__atomic_thread_fence" in stmt:
                m = _ATOMIC_ORDER_RE.search(stmt)
                events.append(("fence", m.group(1) if m else "?"))
                continue
            helper = next((h for h in self.helper_names
                           if re.search(rf"\b{h}\s*\(", stmt)), None)
            if helper:
                events.append(("helper", helper))
                continue
            if re.search(rf"(?:->|\.)\s*{seq_field}\b", stmt) \
                    and "__atomic_" in stmt:
                opm = _ATOMIC_OP_RE.search(stmt)
                m = _ATOMIC_ORDER_RE.search(stmt)
                kind = "store" if opm and opm.group(1).startswith(
                    "store") else "load"
                events.append((kind, m.group(1) if m else "?"))
        return events

    def _match_shape(self, fn: CFunc, got: List[Tuple[str, str]],
                     want: List[Tuple[str, str]], name: str,
                     shape: str) -> None:
        if got != want:
            self.findings.append(Finding(
                "atomics", fn.path, fn.line,
                f"seqlock {name}: {fn.name} does not follow the "
                f"declared shape ({shape}); observed "
                f"{got!r}, expected {want!r} — a missing fence or "
                f"re-check is a torn read on arm64"))


# ---------------------------------------------------------------------------
# Banned constructs (implicit orders)
# ---------------------------------------------------------------------------

_STD_ATOMIC_DECL_RE = re.compile(r"std::atomic<[^>]*>\s+(\w+)")
_STD_ATOMIC_OP = ("load", "store", "exchange", "fetch_add", "fetch_sub",
                  "compare_exchange_weak", "compare_exchange_strong")


def banned_constructs(stripped: str, path: str) -> List[Finding]:
    out: List[Finding] = []
    atomics: Set[str] = set(_STD_ATOMIC_DECL_RE.findall(stripped))
    for i, line in enumerate(stripped.splitlines(), 1):
        if "__sync_" in line:
            out.append(Finding(
                "atomics", path, i,
                "__sync_* builtin: implicit seq_cst with no declared "
                "order — use __atomic_* with the order the protocol "
                "declares"))
        if re.search(r"\bvolatile\b", line):
            out.append(Finding(
                "atomics", path, i,
                "volatile is not a synchronization primitive — use "
                "atomics with explicit orders"))
        for name in atomics:
            for op in _STD_ATOMIC_OP:
                if re.search(rf"\b{name}\s*\.\s*{op}\s*\(", line) \
                        and "memory_order" not in line:
                    out.append(Finding(
                        "atomics", path, i,
                        f"std::atomic `{name}.{op}(...)` without an "
                        f"explicit std::memory_order (implicit "
                        f"seq_cst)"))
            if re.search(rf"\b{name}\s*(\+\+|--|[+\-|&^]=)", line):
                out.append(Finding(
                    "atomics", path, i,
                    f"std::atomic `{name}` mutated via operator "
                    f"(implicit seq_cst RMW) — use an explicit-order "
                    f"method"))
    return out


# ---------------------------------------------------------------------------
# Mirror (layout drift) checks
# ---------------------------------------------------------------------------

def check_mirrors(gt: GroundTruth, structs: Dict[str, List[CField]],
                  defines: Dict[str, int], shim_src: str,
                  const_sources: Dict[str, str]) -> List[Finding]:
    out: List[Finding] = []
    py_structs, py_consts = parse_ctypes_structs(shim_src,
                                                 const_sources)
    for cname, pyfile, pyclass in gt.mirrors:
        clay = c_layout(cname, structs)
        if clay is None:
            out.append(Finding(
                "atomics", HEADER, 1,
                f"mirror: C struct {cname} not found or not "
                f"layout-computable"))
            continue
        pyfields = py_structs.get(pyclass)
        if pyfields is None:
            out.append(Finding(
                "atomics", f"{PKG_NAME}/{pyfile}", 1,
                f"mirror: ctypes class {pyclass} not found in "
                f"{pyfile}"))
            continue
        plan = ctypes_layout(pyfields)
        rel = f"{PKG_NAME}/{pyfile}"
        if plan is None:
            out.append(Finding(
                "atomics", rel, 1,
                f"mirror: {pyclass} uses a ctype or array length the "
                f"checker cannot resolve"))
            continue
        cnames = [n for n, _o, _s in clay]
        pnames = [n for n, _o, _s in plan]
        if cnames != pnames:
            out.append(Finding(
                "atomics", rel, 1,
                f"LAYOUT DRIFT: {cname} fields {cnames} != {pyclass} "
                f"ctypes fields {pnames} (order/name mismatch is "
                f"silent cross-language corruption)"))
            continue
        for (fn_, co, cs), (_pn, po, ps) in zip(clay, plan):
            if co != po or cs != ps:
                out.append(Finding(
                    "atomics", rel, 1,
                    f"LAYOUT DRIFT: {cname}.{fn_} is offset {co} "
                    f"size {cs} in C but offset {po} size {ps} in "
                    f"{pyclass} — the ctypes mirror reads the wrong "
                    f"bytes"))
    for c_const, pyfile, py_const in gt.consts:
        cval = defines.get(c_const)
        pval = py_consts.get(py_const)
        if cval is None:
            out.append(Finding(
                "atomics", HEADER, 1,
                f"mirror-const: #define {c_const} not found in the "
                f"native sources"))
        elif pval is None:
            out.append(Finding(
                "atomics", f"{PKG_NAME}/{pyfile}", 1,
                f"mirror-const: {py_const} not found in {pyfile}"))
        elif cval != pval:
            out.append(Finding(
                "atomics", f"{PKG_NAME}/{pyfile}", 1,
                f"LAYOUT DRIFT: {c_const} = {cval} in C but "
                f"{py_const} = {pval} in {pyfile} — array extents "
                f"disagree across the language boundary"))
    return out


# ---------------------------------------------------------------------------
# crash-atomic layout rule
# ---------------------------------------------------------------------------

def check_crash_atomic(gt: GroundTruth,
                       structs: Dict[str, List[CField]]
                       ) -> List[Finding]:
    out: List[Finding] = []
    for spec in gt.raw.get("crash-atomic", ()):
        if "." not in spec:
            continue
        sname, fname = spec.split(".", 1)
        lay = c_layout(sname, structs)
        if lay is None:
            out.append(Finding(
                "atomics", HEADER, 1,
                f"crash-atomic {spec}: cannot compute the layout of "
                f"{sname}"))
            continue
        ent = next(((o, s) for n, o, s in lay if n == fname), None)
        if ent is None:
            continue  # already reported by category building
        off, size = ent
        if size > 8 or size not in (1, 2, 4, 8) or off % size != 0:
            out.append(Finding(
                "atomics", HEADER, 1,
                f"crash-atomic {spec}: offset {off} size {size} is "
                f"not one naturally-aligned machine word — a "
                f"degraded-mode read can tear while the writer is "
                f"dead mid-update"))
    return out


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------

def check_sources(native_sources: Dict[str, str], shim_src: str,
                  const_sources: Dict[str, str]) -> List[Finding]:
    """Analyze an in-memory tree ({relpath: text} for the native
    files; tests feed seeded-violation fixtures)."""
    header_src = native_sources.get(HEADER)
    if header_src is None:
        return [Finding("atomics", HEADER, 1,
                        "vtpu_core.h missing — cannot load the "
                        "shared-memory protocol ground truth")]
    gt, findings = parse_ground_truth(header_src)
    if gt is None:
        return findings
    stripped = {rel: strip_comments(src)
                for rel, src in native_sources.items()}
    structs, defines = parse_c_structs(stripped)
    checker = _Checker(gt, structs)
    checker.findings.extend(findings)
    checker.build_categories(HEADER)
    funcs: Dict[str, CFunc] = {}
    for rel, src in sorted(stripped.items()):
        if not rel.endswith((".cc", ".c")):
            continue
        for fn in split_functions(src, rel):
            fn.locked = bool(re.search(r"\block_region\s*\(",
                                       " ".join(t for _l, t
                                                in fn.statements)))
            funcs[fn.name] = fn
            checker.scan_function(fn)
    checker.check_pairing(HEADER)
    checker.check_seqlocks(funcs)
    checker.check_rings(funcs)
    out = checker.findings
    for rel, src in sorted(stripped.items()):
        out.extend(banned_constructs(src, rel))
    out.extend(check_crash_atomic(gt, structs))
    out.extend(check_mirrors(gt, structs, defines, shim_src,
                             const_sources))
    # dedup (categories can be hit via several chains per line)
    seen: Set[Tuple[str, int, str]] = set()
    uniq: List[Finding] = []
    for f in out:
        key = (f.path, f.line, f.message)
        if key not in seen:
            seen.add(key)
            uniq.append(f)
    return uniq


def check(root: str) -> List[Finding]:
    native_sources: Dict[str, str] = {}
    for rel in NATIVE_ANALYZED:
        text = read_text(root, rel)
        if text is not None:
            native_sources[rel] = text
    if HEADER not in native_sources:
        return []
    shim_src = read_text(root, SHIM) or ""
    const_sources = {}
    for rel in (SHIM, ENVSPEC):
        text = read_text(root, rel)
        if text is not None:
            const_sources[rel] = text
    return check_sources(native_sources, shim_src, const_sources)