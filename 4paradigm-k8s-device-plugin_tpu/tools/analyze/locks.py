"""Lock-discipline checker for the broker's lock web.

Extracts every ``with <lock>`` nesting arc from the runtime + shim
modules and checks it against the canonical lock order declared in the
``runtime/server.py`` module docstring (the ground truth operators read
— keeping it machine-checked is the whole point).  Also bans blocking
calls (socket I/O, journal writes, fsync, subprocess, sleeps,
condition waits) under the locks the docstring lists as
``no-blocking-under``, with call summaries propagated transitively one
module-set-wide fixpoint deep, so ``drop_array -> _journal_drop ->
journal.append`` is caught even though no journal call is textually
inside the ``with``.

Ground-truth grammar (parsed out of the server docstring)::

    lock-order ground truth (vtpu-analyze):
        order: A > B          # A may be held while acquiring B
        leaf: X, Y            # nothing may be acquired while holding X
        no-blocking-under: X, Y

Declared arcs are closed transitively; an observed arc outside the
closure, an arc out of a ``leaf:`` lock, a same-lock re-entry, or a
cycle in the declared graph itself each produce a finding.  A lock
expression the canonicalizer cannot classify is ALSO a finding — new
locks must be added to the tables below and to the docstring.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from . import Finding, read_text, PKG_NAME

SERVER = f"{PKG_NAME}/runtime/server.py"

# Files whose lock behavior is analyzed (the broker web + everything
# that runs inside tenant processes).
ANALYZED = [
    f"{PKG_NAME}/runtime/server.py",
    f"{PKG_NAME}/runtime/client.py",
    f"{PKG_NAME}/runtime/journal.py",
    f"{PKG_NAME}/runtime/cluster.py",
    f"{PKG_NAME}/runtime/slo.py",
    f"{PKG_NAME}/runtime/trace.py",
    f"{PKG_NAME}/shim/bridge.py",
    f"{PKG_NAME}/shim/core.py",
    f"{PKG_NAME}/shim/pyshim.py",
    f"{PKG_NAME}/shim/sitecustomize.py",
    f"{PKG_NAME}/shim/vtpu_smi_lite.py",
]

# (enclosing class, self-attribute) -> canonical lock name.
CLASS_LOCKS: Dict[Tuple[str, str], str] = {
    ("DeviceScheduler", "mu"): "scheduler.mu",
    ("RuntimeState", "mu"): "state.mu",
    ("RuntimeState", "chips_mu"): "chips_mu",
    ("RuntimeState", "put_cache_mu"): "put_cache_mu",
    ("Tenant", "mu"): "tenant.mu",
    ("TenantSession", "send_mu"): "session.send_mu",
    ("TenantSession", "pending_cond"): "session.pending_cond",
    ("Journal", "mu"): "journal.mu",
    ("Coordinator", "mu"): "coord.mu",
    ("FlightRecorder", "mu"): "flight.mu",
    ("SloPlane", "mu"): "slo.mu",
    ("Bridge", "_mu"): "bridge.mu",
    ("BridgedFunction", "_mu"): "bridge.fn_mu",
    ("_BatchReply", "mu"): "batch.mu",
    ("RateLease", "mu"): "lease.mu",
}

# Bare-name locks (module-level objects).
NAME_LOCKS: Dict[str, str] = {
    "_bridge_mu": "bridge.global_mu",
}

# Non-self attribute tails: (previous chain element, attr) -> canonical.
CHAIN_LOCKS: Dict[Tuple[str, str], str] = {
    ("scheduler", "mu"): "scheduler.mu",
    # migrate_tenant's source/target scheduler handles (same class,
    # same canonical lock — never both held at once).
    ("old_sched", "mu"): "scheduler.mu",
    ("new_sched", "mu"): "scheduler.mu",
    ("state", "mu"): "state.mu",
    ("state", "chips_mu"): "chips_mu",
    ("tenant", "mu"): "tenant.mu",
    ("t", "mu"): "tenant.mu",
    ("coord", "mu"): "coord.mu",
    ("pending_cond", ""): "session.pending_cond",
}

# SharedRegion / native-region methods: each takes the region's robust
# process-shared mutex (canonical innermost lock "region.lock").
REGION_METHODS = {
    "mem_acquire", "mem_acquire_capped", "mem_release", "mem_info",
    "device_stats", "proc_stats", "rate_acquire", "rate_adjust",
    "rate_block", "rate_level", "set_core_limit", "set_mem_limit",
    "set_work_conserving", "reset_slot", "busy_add", "register",
    "deregister", "sweep_dead", "sweep_dead_host", "active_procs",
}

# Directly-blocking callables: attribute tails that do socket I/O,
# durable file I/O or sleeps.  ``wait`` is handled specially (a
# condition wait on the HELD lock releases it and is the sanctioned
# pattern; any other wait is a block).
BLOCKING_ATTRS = {
    "sendall", "recv", "recv_into", "connect", "accept", "fsync",
    "sleep", "send_msg", "recv_msg", "check_call", "check_output",
    "run", "Popen", "communicate", "send_frames", "recv_raw_into",
    "recv_exact_into", "sendmsg", "rate_block",
}
# Journal write methods: file I/O under journal.mu — blocking AND an
# arc to journal.mu.  Matched only when the receiver chain mentions the
# journal (``self.journal.append`` / ``jr.append`` / ``journal.append``)
# so list.append etc. never false-positive.
JOURNAL_WRITE_ATTRS = {"append", "append_many", "put_blob",
                       "write_snapshot"}
JOURNAL_BASES = ("journal", "jr")

_COMMON_METHODS = {
    # never resolved through the unique-name fallback: too generic
    "append", "extend", "get", "pop", "add", "remove", "close", "read",
    "write", "items", "values", "keys", "clear", "update", "join",
    "start", "copy", "popitem", "move_to_end", "discard", "put",
    "send", "setdefault", "split", "strip", "encode", "decode", "wait",
    "notify", "notify_all", "acquire", "release", "get_nowait", "stop",
    "main", "check", "render", "fetch", "delete", "flush", "emit",
}


def _chain(node: ast.AST) -> str:
    """Dotted-ish text of an attribute chain: ``self.chips[0].region.x``
    -> ``self.chips[].region.x`` (subscripts/calls flattened)."""
    if isinstance(node, ast.Attribute):
        return _chain(node.value) + "." + node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Subscript):
        return _chain(node.value) + "[]"
    if isinstance(node, ast.Call):
        return _chain(node.func) + "()"
    return "?"


def canon_lock(node: ast.AST, cls: Optional[str]) -> Optional[str]:
    """Canonical lock name for a ``with`` context expression, or None
    when the expression is not lock-shaped (e.g. ``with open(...)``)."""
    if isinstance(node, ast.Name):
        return NAME_LOCKS.get(node.id)
    if not isinstance(node, ast.Attribute):
        return None
    chain = _chain(node)
    parts = chain.split(".")
    tail = parts[-1]
    if tail not in ("mu", "chips_mu", "put_cache_mu", "send_mu",
                    "pending_cond", "_mu"):
        return None
    if len(parts) == 2 and parts[0] == "self" and cls:
        return CLASS_LOCKS.get((cls, tail))
    prev = parts[-2] if len(parts) >= 2 else ""
    prev = prev.rstrip("[]()")
    if tail == "chips_mu":
        return "chips_mu"
    if tail == "put_cache_mu":
        return "put_cache_mu"
    if tail == "send_mu":
        return "session.send_mu"
    if tail == "pending_cond":
        return "session.pending_cond"
    return CHAIN_LOCKS.get((prev, tail))


# -- ground truth ---------------------------------------------------------

GT_HEADER = "lock-order ground truth (vtpu-analyze):"


class GroundTruth:
    def __init__(self) -> None:
        self.arcs: Set[Tuple[str, str]] = set()
        self.leaves: Set[str] = set()
        self.no_blocking: Set[str] = set()
        self.known: Set[str] = set()

    def closure(self) -> Set[Tuple[str, str]]:
        closed = set(self.arcs)
        changed = True
        while changed:
            changed = False
            for a, b in list(closed):
                for c, d in list(closed):
                    if b == c and (a, d) not in closed and a != d:
                        closed.add((a, d))
                        changed = True
        return closed

    def cycle(self) -> Optional[Tuple[str, str]]:
        return next(((a, b) for a, b in self.closure()
                     if (b, a) in self.closure()), None)


def parse_ground_truth(server_src: str) -> Optional[GroundTruth]:
    """Pull the declared order out of the server module docstring."""
    try:
        tree = ast.parse(server_src)
    except SyntaxError:
        return None
    doc = ast.get_docstring(tree) or ""
    if GT_HEADER not in doc:
        return None
    gt = GroundTruth()
    block = doc.split(GT_HEADER, 1)[1]
    # The block ends at the first blank-line-separated paragraph that
    # carries none of our directives.
    for raw in block.splitlines():
        line = raw.strip()
        m = re.match(r"order:\s*(\S+)\s*>\s*(\S+)", line)
        if m:
            gt.arcs.add((m.group(1), m.group(2)))
            gt.known.update(m.groups())
            continue
        m = re.match(r"(leaf|no-blocking-under):\s*(.+)", line)
        if m:
            names = [t.strip() for t in m.group(2).split(",") if t.strip()]
            if m.group(1) == "leaf":
                gt.leaves.update(names)
            else:
                gt.no_blocking.update(names)
            gt.known.update(names)
    return gt


# -- per-function facts ---------------------------------------------------

class FnFacts:
    def __init__(self, qualname: str, name: str, path: str) -> None:
        self.qualname = qualname
        self.name = name
        self.path = path
        self.locks: Set[str] = set()      # locks acquired directly
        self.blocking: List[Tuple[int, str]] = []  # direct blocking sites
        self.calls: Set[str] = set()      # bare callee names (fallback)


class _FnVisitor(ast.NodeVisitor):
    """Collects, per with-block, the held-lock stack; records arcs,
    direct blocking calls and callee names for the summary fixpoint."""

    def __init__(self, checker: "_Checker", facts: FnFacts,
                 cls: Optional[str]) -> None:
        self.c = checker
        self.facts = facts
        self.cls = cls
        self.stack: List[str] = []

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass  # nested defs get their own facts via _Checker

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_With(self, node: ast.With) -> None:
        pushed = 0
        for item in node.items:
            lock = canon_lock(item.context_expr, self.cls)
            if lock is None:
                expr = item.context_expr
                if isinstance(expr, (ast.Attribute, ast.Name)) and \
                        _chain(expr).split(".")[-1].endswith("mu"):
                    self.c.finding(
                        self.facts.path, expr.lineno,
                        f"unclassifiable lock expression "
                        f"`{_chain(expr)}` in {self.facts.qualname} — "
                        f"extend tools/analyze/locks.py tables and the "
                        f"server docstring ground truth")
                continue
            self.facts.locks.add(lock)
            for held in self.stack:
                self.c.observe(held, lock, self.facts.path,
                               item.context_expr.lineno,
                               self.facts.qualname)
            if lock in self.stack:
                self.c.finding(
                    self.facts.path, item.context_expr.lineno,
                    f"{self.facts.qualname} re-enters {lock} already "
                    f"held (non-reentrant deadlock)")
            self.stack.append(lock)
            pushed += 1
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(pushed):
            self.stack.pop()

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        held = list(self.stack)
        if isinstance(fn, ast.Attribute):
            chain = _chain(fn)
            base_parts = [p.rstrip("[]()")
                          for p in chain.split(".")[:-1]]
            attr = fn.attr
            if attr in REGION_METHODS and "region" in base_parts:
                self.c.touch_lock("region.lock", held, self.facts,
                                  node.lineno)
            elif attr in JOURNAL_WRITE_ATTRS and \
                    any(b in JOURNAL_BASES for b in base_parts):
                self.c.touch_lock("journal.mu", held, self.facts,
                                  node.lineno)
                self.c.block_site(self.facts, held, node.lineno,
                                  f"journal write `{chain}`")
            elif attr in BLOCKING_ATTRS:
                self.c.block_site(self.facts, held, node.lineno,
                                  f"blocking call `{chain}`")
            elif attr == "wait":
                base = canon_lock(fn.value, self.cls)
                if held and base != held[-1]:
                    # waiting on something other than the innermost held
                    # lock blocks while still holding it
                    self.c.block_site(self.facts, held, node.lineno,
                                      f"wait on `{chain}` while holding "
                                      f"{held[-1]}")
                self.facts.blocking.append(
                    (node.lineno, f"condition wait `{chain}`"))
            elif attr not in _COMMON_METHODS:
                self.facts.calls.add(attr)
                self.c.call_site(self.facts, attr, held, node.lineno)
        elif isinstance(fn, ast.Name):
            if fn.id in BLOCKING_ATTRS:
                self.c.block_site(self.facts, held, node.lineno,
                                  f"blocking call `{fn.id}`")
            else:
                self.facts.calls.add(fn.id)
                self.c.call_site(self.facts, fn.id, held, node.lineno)
        self.generic_visit(node)


class _Checker:
    def __init__(self, gt: GroundTruth) -> None:
        self.gt = gt
        self.closure = gt.closure()
        self.findings: List[Finding] = []
        self.fns: Dict[str, List[FnFacts]] = {}
        # (caller facts, callee name, held locks, line)
        self.deferred_calls: List[Tuple[FnFacts, str, List[str], int]] = []

    def finding(self, path: str, line: int, msg: str) -> None:
        self.findings.append(Finding("locks", path, line, msg))

    def observe(self, outer: str, inner: str, path: str, line: int,
                where: str) -> None:
        if outer == inner:
            return
        if outer in self.gt.leaves:
            self.finding(path, line,
                         f"{where} acquires {inner} while holding leaf "
                         f"lock {outer}")
        elif (outer, inner) not in self.closure:
            self.finding(path, line,
                         f"{where} nests {inner} under {outer}: edge not "
                         f"in the declared lock order (server docstring)")

    def touch_lock(self, lock: str, held: List[str], facts: FnFacts,
                   line: int) -> None:
        facts.locks.add(lock)
        for h in held:
            self.observe(h, lock, facts.path, line, facts.qualname)

    def block_site(self, facts: FnFacts, held: List[str], line: int,
                   what: str) -> None:
        facts.blocking.append((line, what))
        for h in held:
            if h in self.gt.no_blocking:
                self.finding(facts.path, line,
                             f"{facts.qualname}: {what} while holding "
                             f"{h} (no-blocking-under)")

    def call_site(self, facts: FnFacts, callee: str, held: List[str],
                  line: int) -> None:
        if held:
            self.deferred_calls.append((facts, callee, list(held), line))

    # -- summaries --------------------------------------------------------

    def resolve(self, name: str) -> Optional[FnFacts]:
        """Unique-name resolution: a callee name matching exactly one
        analyzed function resolves to it; ambiguous or generic names
        are skipped (over-approximation kept one-sided: misses are
        possible, false positives are not)."""
        if name in _COMMON_METHODS:
            return None
        cands = self.fns.get(name, ())
        return cands[0] if len(cands) == 1 else None

    def fixpoint(self) -> Tuple[Dict[str, Set[str]], Dict[str, str]]:
        """Transitive (locks-acquired, blocks?) summaries per function
        qualname, via the unique-name call graph."""
        eff_locks: Dict[str, Set[str]] = {}
        eff_block: Dict[str, str] = {}
        for fl in self.fns.values():
            for f in fl:
                eff_locks[f.qualname] = set(f.locks)
                if f.blocking:
                    eff_block[f.qualname] = f.blocking[0][1]
        changed = True
        while changed:
            changed = False
            for fl in self.fns.values():
                for f in fl:
                    for callee in f.calls:
                        tgt = self.resolve(callee)
                        if tgt is None:
                            continue
                        add = eff_locks.get(tgt.qualname, set()) \
                            - eff_locks[f.qualname]
                        if add:
                            eff_locks[f.qualname] |= add
                            changed = True
                        if tgt.qualname in eff_block and \
                                f.qualname not in eff_block:
                            eff_block[f.qualname] = (
                                f"calls {tgt.qualname} which does "
                                f"{eff_block[tgt.qualname]}")
                            changed = True
        return eff_locks, eff_block

    def check_deferred(self) -> None:
        eff_locks, eff_block = self.fixpoint()
        for facts, callee, held, line in self.deferred_calls:
            tgt = self.resolve(callee)
            if tgt is None:
                continue
            for lock in eff_locks.get(tgt.qualname, ()):
                for h in held:
                    self.observe(h, lock, facts.path, line,
                                 f"{facts.qualname} (via {callee})")
            if tgt.qualname in eff_block:
                for h in held:
                    if h in self.gt.no_blocking:
                        self.finding(
                            facts.path, line,
                            f"{facts.qualname}: call to {callee} "
                            f"({eff_block[tgt.qualname]}) while holding "
                            f"{h} (no-blocking-under)")


def check_sources(sources: Dict[str, str],
                  server_rel: str = SERVER) -> List[Finding]:
    """Analyze a {relpath: text} tree (tests feed fixture snippets)."""
    server_src = sources.get(server_rel)
    if server_src is None:
        return [Finding("locks", server_rel, 1,
                        "server module missing — cannot load lock-order "
                        "ground truth")]
    gt = parse_ground_truth(server_src)
    if gt is None:
        return [Finding("locks", server_rel, 1,
                        f"module docstring has no `{GT_HEADER}` block — "
                        f"the canonical lock order must be declared")]
    cyc = gt.cycle()
    if cyc is not None:
        return [Finding("locks", server_rel, 1,
                        f"declared lock order is cyclic: "
                        f"{cyc[0]} <-> {cyc[1]}")]
    checker = _Checker(gt)
    # pass 1: per-function facts
    visits: List[Tuple[_FnVisitor, ast.FunctionDef]] = []
    for rel, src in sorted(sources.items()):
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            checker.finding(rel, e.lineno or 1, f"syntax error: {e.msg}")
            continue
        # Innermost enclosing class per function (ast.walk is BFS, so a
        # nested class's pass overwrites its outer class's entry).
        cls_of: Dict[ast.AST, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                for sub in ast.walk(node):
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        cls_of[sub] = node.name
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            cls = cls_of.get(node)
            qual = f"{cls}.{node.name}" if cls else node.name
            name = cls if node.name == "__init__" and cls else node.name
            facts = FnFacts(f"{rel}:{qual}", name, rel)
            checker.fns.setdefault(name, []).append(facts)
            visits.append((_FnVisitor(checker, facts, cls), node))
    for visitor, node in visits:
        for stmt in node.body:
            visitor.visit(stmt)
    # pass 2: transitive summaries against the recorded call sites
    checker.check_deferred()
    # every canonical lock seen must be declared somewhere in the GT
    for fl in checker.fns.values():
        for f in fl:
            for lock in f.locks:
                if lock not in gt.known:
                    checker.finding(
                        f.path, 1,
                        f"lock {lock} (used in {f.qualname}) is not "
                        f"mentioned in the ground-truth block")
    # dedup (the same arc can be observed via many paths)
    seen: Set[Tuple[str, int, str]] = set()
    out = []
    for f in checker.findings:
        key = (f.path, f.line, f.message)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out


def check(root: str) -> List[Finding]:
    sources = {}
    for rel in ANALYZED:
        text = read_text(root, rel)
        if text is not None:
            sources[rel] = text
    if SERVER not in sources:
        return []
    return check_sources(sources)
