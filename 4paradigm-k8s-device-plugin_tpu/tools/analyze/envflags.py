"""Env-flag contract checker.

``VTPU_*`` environment variables are the ONLY channel between the
daemon and the in-container enforcement layer, and they are read from
four different languages/layers (Python shim/broker/daemon, native
C++, bench tooling).  The contract lives in ``utils/envspec.py``'s
flag registry; this checker proves:

  - every ``VTPU_*`` literal read anywhere in the Python tree (through
    ``os.environ.get`` / ``os.getenv`` / ``"X" in os.environ`` /
    config's ``_env`` helper) or the native tree (``getenv("VTPU_…")``)
    is declared in the registry (per-ordinal ``VTPU_DEVICE_HBM_LIMIT_<i>``
    forms match their declared prefix);
  - no raw ``os.environ["VTPU_*"]`` subscript read bypasses the
    ``.get()``/envspec path (subscript WRITES — the producer side — are
    fine);
  - every registered flag is documented in ``docs/FLAGS.md``;
  - every flag marked as a Helm-surfaced operator tunable appears in
    ``deployments/helm/vtpu-device-plugin/values.yaml``.

The registry itself is parsed from envspec with ``ast.literal_eval``
(this checker must not import product modules — CI runs it without the
runtime deps installed).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from . import Finding, read_text, PKG_NAME

ENVSPEC = f"{PKG_NAME}/utils/envspec.py"
FLAGS_MD = "docs/FLAGS.md"
HELM_VALUES = "deployments/helm/vtpu-device-plugin/values.yaml"

# Python files scanned for reads: the whole package + bench tooling.
PY_SCAN_DIRS = (PKG_NAME,)
PY_SCAN_FILES = ("bench.py", "__graft_entry__.py")
NATIVE_DIR = "native"

ENV_READ_FUNCS = {"getenv", "_env"}
_GETENV_RE = re.compile(r'getenv\(\s*"(VTPU_[A-Z0-9_]+)"')
_TOKEN_RE = re.compile(r"VTPU_[A-Z0-9_]+")


def parse_registry(envspec_src: str, path: str = ENVSPEC
                   ) -> Tuple[Dict[str, bool], Tuple[str, ...],
                              List[Finding]]:
    """(declared {flag: helm?}, prefixes, findings) from envspec's
    ``ENV_FLAGS`` / ``ENV_FLAG_PREFIXES`` / ``ALL_ENV_VARS`` blocks —
    extracted syntactically, no import."""
    findings: List[Finding] = []
    declared: Dict[str, bool] = {}
    prefixes: List[str] = []
    try:
        tree = ast.parse(envspec_src)
    except SyntaxError as e:
        return {}, (), [Finding("envflags", path, e.lineno or 1,
                                f"syntax error: {e.msg}")]
    consts: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            consts[node.targets[0].id] = node.value.value

    def resolve(el: ast.AST) -> Optional[str]:
        if isinstance(el, ast.Constant) and isinstance(el.value, str):
            return el.value
        if isinstance(el, ast.Name):
            return consts.get(el.id)
        if isinstance(el, ast.BinOp) and isinstance(el.op, ast.Add):
            a, b = resolve(el.left), resolve(el.right)
            return a + b if a is not None and b is not None else None
        return None

    found_registry = False
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1 \
                or not isinstance(node.targets[0], ast.Name):
            continue
        name = node.targets[0].id
        if name == "ENV_FLAGS" and isinstance(node.value, ast.Dict):
            found_registry = True
            for k, v in zip(node.value.keys, node.value.values):
                flag = resolve(k) if k is not None else None
                if flag is None:
                    findings.append(Finding(
                        "envflags", path, node.lineno,
                        "ENV_FLAGS key is not a resolvable string"))
                    continue
                helm = False
                if isinstance(v, (ast.Tuple, ast.List)) and v.elts:
                    last = v.elts[-1]
                    helm = isinstance(last, ast.Constant) and \
                        last.value is True
                declared[flag] = helm
        elif name == "ENV_FLAG_PREFIXES" and \
                isinstance(node.value, (ast.Tuple, ast.List)):
            for el in node.value.elts:
                p = resolve(el)
                if p:
                    prefixes.append(p)
    if not found_registry:
        findings.append(Finding(
            "envflags", path, 1,
            "utils/envspec.py has no ENV_FLAGS registry"))
    return declared, tuple(prefixes), findings


def _env_chain(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute):
        return _env_chain(node.value) + "." + node.attr
    if isinstance(node, ast.Name):
        return node.id
    return "?"


def python_reads(src: str, rel: str) -> Tuple[List[Tuple[str, int]],
                                              List[Tuple[str, int]]]:
    """(env reads [(flag, line)], raw subscript reads [(flag, line)])
    of VTPU_* literals in one Python source."""
    reads: List[Tuple[str, int]] = []
    raw: List[Tuple[str, int]] = []
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return reads, raw
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fn = node.func
            lit: Optional[str] = None
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str) \
                    and _TOKEN_RE.fullmatch(node.args[0].value):
                lit = node.args[0].value
            if lit is None:
                continue
            if isinstance(fn, ast.Attribute):
                chain = _env_chain(fn)
                if chain.endswith("environ.get") or \
                        chain.endswith("os.getenv"):
                    reads.append((lit, node.lineno))
            elif isinstance(fn, ast.Name) and fn.id in ENV_READ_FUNCS:
                reads.append((lit, node.lineno))
        elif isinstance(node, ast.Subscript):
            if not _env_chain(node.value).endswith("environ"):
                continue
            sl = node.slice
            if isinstance(sl, ast.Constant) and \
                    isinstance(sl.value, str) and \
                    _TOKEN_RE.fullmatch(sl.value):
                if isinstance(node.ctx, ast.Load):
                    reads.append((sl.value, node.lineno))
                    raw.append((sl.value, node.lineno))
        elif isinstance(node, ast.Compare) and \
                isinstance(node.left, ast.Constant) and \
                isinstance(node.left.value, str) and \
                _TOKEN_RE.fullmatch(node.left.value) and \
                any(isinstance(op, ast.In) for op in node.ops) and \
                any(_env_chain(c).endswith("environ")
                    for c in node.comparators):
            reads.append((node.left.value, node.lineno))
    return reads, raw


def native_reads(src: str) -> List[Tuple[str, int]]:
    out = []
    for i, line in enumerate(src.splitlines(), 1):
        for m in _GETENV_RE.finditer(line):
            out.append((m.group(1), i))
    return out


def _declared(flag: str, declared: Dict[str, bool],
              prefixes: Tuple[str, ...]) -> bool:
    if flag in declared:
        return True
    return any(flag.startswith(p) and flag[len(p):].isdigit()
               for p in prefixes)


def check_tree(py_sources: Dict[str, str], native_sources: Dict[str, str],
               envspec_src: str, flags_md: str, helm_values: str
               ) -> List[Finding]:
    declared, prefixes, findings = parse_registry(envspec_src)
    if not declared:
        return findings
    for rel, src in sorted(py_sources.items()):
        reads, raw = python_reads(src, rel)
        for flag, line in raw:
            findings.append(Finding(
                "envflags", rel, line,
                f'raw os.environ["{flag}"] subscript read bypasses '
                f"envspec — use .get() (or the envspec accessor)"))
        for flag, line in reads:
            if not _declared(flag, declared, prefixes):
                findings.append(Finding(
                    "envflags", rel, line,
                    f"{flag} is read here but not declared in "
                    f"utils/envspec.py ENV_FLAGS"))
    for rel, src in sorted(native_sources.items()):
        for flag, line in native_reads(src):
            if not _declared(flag, declared, prefixes):
                findings.append(Finding(
                    "envflags", rel, line,
                    f"{flag} is read by native code but not declared "
                    f"in utils/envspec.py ENV_FLAGS"))
    md_tokens = set(_TOKEN_RE.findall(flags_md))
    helm_tokens = set(_TOKEN_RE.findall(helm_values))
    for flag in sorted(declared):
        if flag not in md_tokens:
            findings.append(Finding(
                "envflags", FLAGS_MD, 1,
                f"{flag} is declared in envspec but undocumented in "
                f"docs/FLAGS.md"))
        if declared[flag] and flag not in helm_tokens:
            findings.append(Finding(
                "envflags", HELM_VALUES, 1,
                f"{flag} is marked helm-surfaced but absent from the "
                f"chart values"))
    return findings


def check(root: str) -> List[Finding]:
    envspec_src = read_text(root, ENVSPEC)
    flags_md = read_text(root, FLAGS_MD)
    helm_values = read_text(root, HELM_VALUES)
    if envspec_src is None or flags_md is None or helm_values is None:
        return []
    py_sources: Dict[str, str] = {}
    for base in PY_SCAN_DIRS:
        basedir = os.path.join(root, base)
        for dirpath, _dirs, files in os.walk(basedir):
            for fname in files:
                if not fname.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fname)
                rel = os.path.relpath(full, root)
                text = read_text(root, rel)
                if text is not None:
                    py_sources[rel] = text
    for rel in PY_SCAN_FILES:
        text = read_text(root, rel)
        if text is not None:
            py_sources[rel] = text
    native_sources: Dict[str, str] = {}
    for dirpath, _dirs, files in os.walk(os.path.join(root, NATIVE_DIR)):
        for fname in files:
            if fname.endswith((".cc", ".h", ".c")):
                full = os.path.join(dirpath, fname)
                rel = os.path.relpath(full, root)
                text = read_text(root, rel)
                if text is not None:
                    native_sources[rel] = text
    return check_tree(py_sources, native_sources, envspec_src,
                      flags_md, helm_values)
