"""Journal record-schema checker.

The crash-safe journal (runtime/journal.py) is only as good as its
replay: a record type the broker writes but ``_apply_record`` does not
handle silently loses that state class on every recovery (the
forward-compat "skip unknown ops" clause turns a typo into data loss).
This checker extracts:

  - **writers** — every ``{"op": "<literal>", ...}`` dict passed to a
    journal ``append`` call anywhere in the runtime package, including
    records accumulated into a local list that later feeds a journal
    ``append_many`` batch (the metering loop's wake-batched EMA
    samples);
  - **handlers** — every ``op == "<literal>"`` comparison inside
    ``_apply_record``;

and proves writers == handlers, both directions: an unreplayed written
op is recovery data loss, a handler nothing writes is a dead replay arm
(usually a renamed writer that silently orphaned its records).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from . import Finding, read_text, PKG_NAME

JOURNAL = f"{PKG_NAME}/runtime/journal.py"
WRITER_FILES = (
    f"{PKG_NAME}/runtime/server.py",
    f"{PKG_NAME}/runtime/journal.py",
    f"{PKG_NAME}/runtime/trace.py",
)
JOURNAL_BASES = ("journal", "jr")


def _chain(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute):
        return _chain(node.value) + "." + node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Subscript):
        return _chain(node.value) + "[]"
    if isinstance(node, ast.Call):
        return _chain(node.func) + "()"
    return "?"


def written_ops(src: str, rel: str) -> Dict[str, Tuple[str, int]]:
    """{op: (file, line)} for every journal append of an op-bearing
    record literal."""
    out: Dict[str, Tuple[str, int]] = {}
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return out

    def dict_op(node: ast.AST) -> Optional[str]:
        if not isinstance(node, ast.Dict):
            return None
        for k, v in zip(node.keys, node.values):
            if isinstance(k, ast.Constant) and k.value == "op" and \
                    isinstance(v, ast.Constant) and \
                    isinstance(v.value, str):
                return v.value
        return None

    # `rec = {"op": ...}` then `jr.append(rec)` is the common shape —
    # resolve simple Name arguments through local record literals.
    named: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            op = dict_op(node.value)
            if op is not None:
                named[node.targets[0].id] = op

    def is_journal_call(node: ast.AST, attr: str) -> bool:
        if not isinstance(node, ast.Call) or \
                not isinstance(node.func, ast.Attribute) or \
                node.func.attr != attr:
            return False
        base_parts = [p.rstrip("[]()") for p in
                      _chain(node.func.value).split(".")]
        return any(b in JOURNAL_BASES for b in base_parts) or \
            "pending_journal" in base_parts

    # Lists whose contents feed a batched `journal.append_many(lst)`:
    # every `lst.append({"op": ...})` is then a writer too.
    many_lists: Set[str] = set()
    for node in ast.walk(tree):
        if is_journal_call(node, "append_many"):
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    many_lists.add(arg.id)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or \
                not isinstance(node.func, ast.Attribute) or \
                node.func.attr != "append":
            continue
        if not is_journal_call(node, "append"):
            base = node.func.value
            if not (isinstance(base, ast.Name) and
                    base.id in many_lists):
                continue
        for arg in node.args:
            op = dict_op(arg)
            if op is None and isinstance(arg, ast.Name):
                op = named.get(arg.id)
            if op is not None:
                out.setdefault(op, (rel, node.lineno))
    return out


def handled_ops(journal_src: str) -> Set[str]:
    """Ops ``_apply_record`` replays: ``op == "<lit>"`` comparisons."""
    out: Set[str] = set()
    try:
        tree = ast.parse(journal_src)
    except SyntaxError:
        return out
    fn = None
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and \
                node.name == "_apply_record":
            fn = node
            break
    if fn is None:
        return out
    for node in ast.walk(fn):
        if not isinstance(node, ast.Compare):
            continue
        sides = [node.left] + list(node.comparators)
        names = [s for s in sides
                 if isinstance(s, ast.Name) and s.id == "op"]
        lits = [s.value for s in sides
                if isinstance(s, ast.Constant) and isinstance(s.value, str)]
        if names and lits:
            out.update(lits)
    return out


def check_texts(sources: Dict[str, str], journal_rel: str = JOURNAL
                ) -> List[Finding]:
    journal_src = sources.get(journal_rel)
    if journal_src is None:
        return [Finding("journal", journal_rel, 1,
                        "runtime/journal.py missing — cannot check "
                        "replay coverage")]
    handled = handled_ops(journal_src)
    if not handled:
        return [Finding("journal", journal_rel, 1,
                        "cannot locate _apply_record op handlers")]
    written: Dict[str, Tuple[str, int]] = {}
    for rel, src in sorted(sources.items()):
        for op, where in written_ops(src, rel).items():
            written.setdefault(op, where)
    findings: List[Finding] = []
    for op in sorted(set(written) - handled):
        rel, line = written[op]
        findings.append(Finding(
            "journal", rel, line,
            f'journal record op "{op}" is written here but has no '
            f"replay handler in _apply_record — it is silently lost "
            f"on recovery"))
    for op in sorted(handled - set(written)):
        findings.append(Finding(
            "journal", journal_rel, 1,
            f'_apply_record handles op "{op}" but nothing writes it '
            f"(dead replay arm / renamed writer)"))
    return findings


def check(root: str) -> List[Finding]:
    sources = {}
    for rel in WRITER_FILES:
        text = read_text(root, rel)
        if text is not None:
            sources[rel] = text
    if JOURNAL not in sources:
        return []
    return check_texts(sources)
