"""vtpu-analyze — project-specific cross-layer invariant linters.

The reference repo's CI stops at ``golint``/``go vet``; this reproduction
has grown hand-maintained contracts that generic linters cannot see:

  - **locks** — the broker's lock web (seven locks/conditions across
    ``runtime/server.py``): every observed ``with <lock>`` nesting must
    be covered by the canonical lock order declared in the server module
    docstring, and no blocking call (socket I/O, journal writes, fsync,
    subprocess, sleeps) may run under a fast broker lock.
  - **verbs** — every protocol verb must have a broker dispatch arm, a
    client binding, and (bind-free verbs) precede the NO_HELLO guard on
    the tenant socket and be served on the admin socket.
  - **envflags** — every ``VTPU_*`` env var read anywhere in Python or
    C++ must be declared in ``utils/envspec.py``'s flag registry,
    documented in ``docs/FLAGS.md``, surfaced in the Helm values when
    marked as an operator tunable, and never read via a raw
    ``os.environ["VTPU_*"]`` subscript.
  - **journal** — every record type the broker writes must have a
    replay handler in ``runtime/journal.py`` recovery (and vice versa:
    no dead replay arms).
  - **excsafety** — every region/ledger/bucket acquire in ``runtime/``
    and ``shim/`` must settle on all exception paths: released in the
    handler/finally, or durably owned before any risky call.
  - **wirefields** — every OPTIONAL wire field a newer client may send
    is registered in ``protocol.py``'s ``WIRE_FIELDS`` and read with a
    legacy-default ``.get`` on the serving side; an unregistered
    optional read (or a subscript read of a registered one) fails CI.
  - **atomics** — the static half of vtpu-wmm: every access to an
    mmap'd shared-region field in ``native/vtpucore`` must conform to
    the protocol declared in the ``vtpu_core.h`` comment grammar
    (lock/stable/crash-atomic/publish/seqlock categories with explicit
    memory orders), publish/consume pairings hold in both directions,
    ``__sync_*``/``volatile``/implicit-seq_cst are banned, and the
    ``shim/core.py`` ctypes mirrors match the C struct layouts
    field-for-field (offset/size) — the dynamic half is the
    ``tools/wmm`` litmus explorer.
  - **clusterproto** — the static half of vtpu-dmc: every federation
    verb in ``runtime/cluster.py`` must be registered in
    ``CLUSTER_VERBS`` with a dispatch arm, a sender binding and an
    idempotency class matching the dance grammar declared in the
    cluster module docstring; every journaled cluster op must have a
    replay arm and a reserve/release pairing; dance-message
    idempotency must agree with ``protocol.py``'s retry tables — the
    dynamic half is the ``tools/dmc`` network-fault explorer.

Run as ``python -m vtpu.tools.analyze`` or ``vtpu-smi analyze``; CI runs
it in the ``analyze`` job and fails on any finding.  There is NO
baseline/suppression mechanism on purpose: the tree stays at zero.

Extending: each checker is a module exposing ``check(root) -> list
[Finding]`` plus pure helpers that tests drive with seeded-violation
fixture sources (tests/test_analyze.py) — see docs/ANALYSIS.md.

This package is deliberately stdlib-only (ast + re): the CI job that
runs it needs no jax/msgpack install.
"""

from __future__ import annotations

import argparse
import json
import os
from dataclasses import dataclass
from typing import List, Optional

PKG_DIR = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
REPO_ROOT = os.path.dirname(PKG_DIR)
PKG_NAME = os.path.basename(PKG_DIR)


@dataclass(frozen=True)
class Finding:
    checker: str   # locks | verbs | envflags | journal | excsafety
    #              # | wirefields | atomics | clusterproto
    path: str      # repo-relative
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.checker}] {self.message}"


def read_text(root: str, relpath: str) -> Optional[str]:
    """Source text of ``relpath`` under ``root``; None when absent (a
    fixture tree may carry only the files a test seeds)."""
    path = os.path.join(root, relpath)
    try:
        with open(path, encoding="utf-8") as f:
            return f.read()
    except OSError:
        return None


def run_all(root: Optional[str] = None) -> List[Finding]:
    from . import (atomics, clusterproto, envflags, excsafety,
                   journal_schema, locks, verbs, wirefields)
    root = root or REPO_ROOT
    out: List[Finding] = []
    for mod in (locks, verbs, envflags, journal_schema, excsafety,
                wirefields, atomics, clusterproto):
        out.extend(mod.check(root))
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="vtpu-analyze",
        description="cross-layer invariant linters (docs/ANALYSIS.md)")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--root", default=None,
                    help="repo root to analyze (default: this checkout)")
    ns = ap.parse_args(argv)
    findings = run_all(ns.root)
    if ns.json:
        print(json.dumps([f.__dict__ for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
        print(f"vtpu-analyze: {len(findings)} finding(s)")
    return 1 if findings else 0
