"""Exception-safety checker for region/ledger/bucket acquires.

Every acquire against the shared accounting region — HBM ledger
(``mem_acquire``/``mem_acquire_capped``/``charge_array``) or token
bucket (``rate_acquire``/``rate_acquire_all``) — creates a debt that
MUST be settled on every exception path: either released
(``mem_release``/``rate_adjust``/``release_array``/...), or durably
recorded against an owner whose teardown releases it (an ownership
store into the tenant's ledger books: ``t.arrays[...] = ``,
``t.charges[...] = ``, ...).  A path that raises between the acquire
and either settlement leaks quota forever — the bug class behind
"released tenant still holds HBM" incidents, and exactly what the mc
interleaving engine's hbm-ledger/token-conservation invariants detect
dynamically.  This checker proves it statically, on all paths:

  - **swallowed-handler rule**: an acquire inside a ``try`` whose
    handler catches-and-continues (no ``raise`` in the handler body)
    must be released in that handler (or ``finally``) — directly or
    via a call to a function that releases (one summary fixpoint).
    An ownership store reached from the acquire through only-safe
    statements also settles it, UNLESS the handler ``continue``s (the
    owner is being discarded — its books die with it, the release
    duty stays with the handler).
  - **unprotected-risk rule**: an acquire NOT inside any ``try``,
    followed in the same function by a risky call (device transfer,
    journal/file/socket I/O, compile) before any release or ownership
    store, is a finding — the risky call's exception unwinds past the
    un-settled debt.

Failure branches guarded by the acquire's own result (``admitted =
region.mem_acquire(...); if not admitted: raise``) are exempt: a
refused acquire charges nothing.

Like every vtpu-analyze checker this is TUNED to the repo's idioms
(the tables below are part of the contract): new acquire/release
spellings must be added here, and an unclassifiable pattern is a
finding, not a pass.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from . import Finding, read_text, PKG_NAME

ANALYZED = [
    f"{PKG_NAME}/runtime/server.py",
    f"{PKG_NAME}/runtime/client.py",
    f"{PKG_NAME}/runtime/journal.py",
    f"{PKG_NAME}/runtime/cluster.py",
    f"{PKG_NAME}/runtime/trace.py",
    f"{PKG_NAME}/shim/bridge.py",
    f"{PKG_NAME}/shim/core.py",
    f"{PKG_NAME}/shim/pyshim.py",
    f"{PKG_NAME}/shim/sitecustomize.py",
]

# Acquire family: calls that create region/ledger/bucket debt.  The
# native ctypes trampolines (_c_*) count as their Python spellings.
ACQUIRES = ("mem_acquire", "mem_acquire_capped", "rate_acquire",
            "rate_acquire_all", "charge_array")
# Release family: calls that settle it.
RELEASES = ("mem_release", "release_array", "rate_adjust",
            "rate_adjust_all", "lease_release", "drop_staged",
            "evict_staged_for", "busy_add")
# Ownership stores: subscript assignment into a ledger book whose
# owner's teardown path releases the debt.
OWNER_BOOKS = ("arrays", "charges", "staged", "host_arrays",
               "staged_bytes", "nbytes", "blob_meta")
# Risky calls: operations that raise in practice (device transfer,
# (de)serialization, journal/file/socket I/O, XLA compile).
RISKY_ATTRS = ("device_put", "block_until_ready", "put_blob",
               "append_many", "write_snapshot", "frombuffer", "asarray",
               "ascontiguousarray", "chain_fn", "tenant_program",
               "cached_blob", "send_msg", "send_frames", "sendall",
               "sendmsg", "recv", "recv_into", "recv_raw_into",
               "fsync", "deserialize", "compile", "lower")
# Calls considered incapable of raising in these code paths — the
# safe-walk between an acquire and its ownership store may cross them.
SAFE_ATTRS = ("get", "pop", "items", "values", "keys", "append",
              "add", "discard", "update", "setdefault", "move_to_end",
              "hexdigest", "sha256", "put_cache_get", "device_stats",
              "mem_info", "rate_level", "debug", "info", "warn",
              "error", "monotonic", "time", "acquire", "release",
              "notify", "notify_all", "clear", "copy", "encode",
              "decode", "join", "startswith", "endswith", "reshape",
              "toreadonly", "cast")
SAFE_NAMES = ("int", "str", "float", "bool", "len", "list", "dict",
              "tuple", "set", "max", "min", "abs", "isinstance",
              "memoryview", "bytes", "bytearray", "sorted", "zip",
              "range", "enumerate", "id", "repr", "getattr", "hasattr",
              "print")


def _attr_of(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Attribute):
        a = call.func.attr
        return a[3:] if a.startswith("_c_") else a
    if isinstance(call.func, ast.Name):
        return call.func.id
    return None


def _is_acquire(call: ast.Call) -> bool:
    return _attr_of(call) in ACQUIRES


def _is_release(call: ast.Call) -> bool:
    return _attr_of(call) in RELEASES


def _is_journal_append(call: ast.Call) -> bool:
    """``jr.append(...)`` / ``journal.append(...)`` is file I/O (the
    generic list ``.append`` is safe)."""
    if not (isinstance(call.func, ast.Attribute)
            and call.func.attr == "append"):
        return False
    base = call.func.value
    parts: List[str] = []
    while isinstance(base, ast.Attribute):
        parts.append(base.attr)
        base = base.value
    if isinstance(base, ast.Name):
        parts.append(base.id)
    return any(p in ("journal", "jr") for p in parts)


def _is_risky(call: ast.Call) -> bool:
    return _attr_of(call) in RISKY_ATTRS or _is_journal_append(call)


def _release_summaries(tree: ast.Module) -> Set[str]:
    """Function names that (transitively, one fixpoint) perform a
    release-family call — a handler calling one of these settles the
    debt."""
    bodies: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            bodies[node.name] = node
    releasing: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for name, fn in bodies.items():
            if name in releasing:
                continue
            for call in (n for n in ast.walk(fn)
                         if isinstance(n, ast.Call)):
                callee = _attr_of(call)
                if _is_release(call) or callee in releasing:
                    releasing.add(name)
                    changed = True
                    break
    return releasing


def _body_releases(stmts: List[ast.stmt], releasing_fns: Set[str]
                   ) -> bool:
    for node in (n for s in stmts for n in ast.walk(s)):
        if isinstance(node, ast.Call) and (
                _is_release(node) or _attr_of(node) in releasing_fns):
            return True
    return False


def _body_raises(stmts: List[ast.stmt]) -> bool:
    return any(isinstance(n, ast.Raise)
               for s in stmts for n in ast.walk(s))


def _body_continues(stmts: List[ast.stmt]) -> bool:
    return any(isinstance(n, ast.Continue)
               for s in stmts for n in ast.walk(s))


def _acquire_result_name(stmt: ast.stmt, call: ast.Call
                         ) -> Optional[str]:
    """``admitted = ...mem_acquire(...)`` -> "admitted"."""
    if isinstance(stmt, ast.Assign) and stmt.value is call and \
            len(stmt.targets) == 1 and \
            isinstance(stmt.targets[0], ast.Name):
        return stmt.targets[0].id
    return None


def _guarded_by(node: ast.stmt, name: Optional[str]) -> bool:
    """Is ``node`` an ``if`` whose test references the acquire's
    result name (the refused-acquire failure branch)?"""
    if name is None or not isinstance(node, ast.If):
        return False
    return any(isinstance(n, ast.Name) and n.id == name
               for n in ast.walk(node.test))


def _ownership_settles(stmts: List[ast.stmt], after_line: int,
                       result_name: Optional[str]) -> bool:
    """Walk the statements after the acquire: True when an ownership
    store is reached through only-safe operations (a failure branch
    guarded by the acquire result is skipped).  Compound statements
    are expanded to their LEAVES — only a leaf's own expressions are
    judged, so a ``with``/``if`` container is not condemned for an
    unsafe call deep inside a branch that starts with the ownership
    store."""
    flat: List[ast.stmt] = []

    def _expand(seq: List[ast.stmt]) -> None:
        for s in seq:
            if _guarded_by(s, result_name):
                continue
            sub = [x for attr in ("body", "orelse", "finalbody")
                   for x in (getattr(s, attr, []) or [])]
            if sub and not isinstance(s, ast.Try):
                _expand(sub)
            else:
                flat.append(s)

    _expand(stmts)
    for s in sorted(flat, key=lambda x: x.lineno):
        if s.lineno <= after_line:
            continue
        if _stores_ownership(s):
            return True
        if not _stmt_safe(s):
            return False
    return False


def _stores_ownership(stmt: ast.stmt) -> bool:
    targets: List[ast.expr] = []
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, ast.AugAssign):
        targets = [stmt.target]
    for t in targets:
        if isinstance(t, ast.Subscript) and \
                isinstance(t.value, ast.Attribute) and \
                t.value.attr in OWNER_BOOKS:
            return True
    return False


def _stmt_safe(stmt: ast.stmt) -> bool:
    if isinstance(stmt, (ast.Raise, ast.Try)):
        return False
    for node in ast.walk(stmt):
        if isinstance(node, ast.Call):
            a = _attr_of(node)
            if _is_journal_append(node):
                return False
            if a in ACQUIRES or a in RELEASES:
                continue
            if a in SAFE_ATTRS or a in SAFE_NAMES:
                continue
            return False
    return True


class _FnScan(ast.NodeVisitor):
    """Per-function scan: acquire sites with their enclosing-try
    stacks, and every risky call site."""

    def __init__(self) -> None:
        self.try_stack: List[ast.Try] = []
        # (stmt, call, [tries innermost-first], arm stmts)
        self.acquires: List[Tuple[ast.stmt, ast.Call, List[ast.Try]]] = []
        self.riskies: List[Tuple[ast.Call, List[ast.Try]]] = []
        self._stmt: Optional[ast.stmt] = None

    def visit_Try(self, node: ast.Try) -> None:
        self.try_stack.append(node)
        for s in node.body + node.orelse:
            self.visit(s)
        self.try_stack.pop()
        for h in node.handlers:
            for s in h.body:
                self.visit(s)
        for s in node.finalbody:
            self.visit(s)

    def generic_visit(self, node: ast.AST) -> None:
        if isinstance(node, ast.stmt):
            self._stmt = node
        if isinstance(node, ast.Call):
            if _is_acquire(node):
                self.acquires.append(
                    (self._stmt, node, list(reversed(self.try_stack))))
            elif _is_risky(node):
                self.riskies.append(
                    (node, list(reversed(self.try_stack))))
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not self._root:
            return  # nested defs scanned separately
        super().generic_visit(node)

    def scan(self, fn: ast.AST) -> "_FnScan":
        self._root = fn
        for s in fn.body:
            self.visit(s)
        return self


def check_texts(sources: Dict[str, str]) -> List[Finding]:
    findings: List[Finding] = []
    for rel, src in sorted(sources.items()):
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            findings.append(Finding("excsafety", rel, e.lineno or 1,
                                    f"unparseable: {e.msg}"))
            continue
        releasing = _release_summaries(tree)
        for fn in (n for n in ast.walk(tree)
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))):
            scan = _FnScan().scan(fn)
            for stmt, call, tries in scan.acquires:
                findings.extend(_check_site(
                    rel, fn, stmt, call, tries, scan, releasing))
    return findings


def _check_site(rel: str, fn: ast.AST, stmt: ast.stmt, call: ast.Call,
                tries: List[ast.Try], scan: _FnScan,
                releasing: Set[str]) -> List[Finding]:
    what = _attr_of(call)
    result_name = _acquire_result_name(stmt, call)
    # -- swallowed-handler rule ----------------------------------------
    for t in tries:
        settled = (_body_releases(
            [s for h in t.handlers for s in h.body], releasing)
            or _body_releases(t.finalbody, releasing))
        if settled:
            return []
        swallowing = [h for h in t.handlers if not _body_raises(h.body)]
        if not swallowing:
            continue  # every handler re-raises: walk outward
        handler_continues = any(_body_continues(h.body)
                                for h in swallowing)
        if not handler_continues and _ownership_settles(
                t.body, call.lineno, result_name):
            return []
        return [Finding(
            "excsafety", rel, call.lineno,
            f"{what}() inside a try whose handler catches-and-"
            f"continues (line {swallowing[0].lineno}) without "
            f"releasing: an exception after the acquire leaks the "
            f"charge — release in the handler/finally"
            + (" (the handler 'continue's past the owner, so the "
               "ownership store does not settle it)"
               if handler_continues else ""))]
    # -- unprotected-risk rule -----------------------------------------
    if _ownership_settles(
            getattr(fn, "body", []), call.lineno, result_name):
        return []
    for risky, rtries in scan.riskies:
        if risky.lineno <= call.lineno:
            continue
        protected = any(
            _body_releases([s for h in t.handlers for s in h.body],
                           releasing)
            or _body_releases(t.finalbody, releasing)
            for t in rtries)
        if protected:
            continue
        # A release-family call between acquire and risk settles it.
        if any(_is_release(n) or _attr_of(n) in releasing
               for n in ast.walk(fn)
               if isinstance(n, ast.Call)
               and call.lineno < n.lineno <= risky.lineno):
            break
        return [Finding(
            "excsafety", rel, call.lineno,
            f"{what}() is followed by {_attr_of(risky)}() (line "
            f"{risky.lineno}) with no try releasing on failure and no "
            f"ownership store in between: an exception there leaks "
            f"the charge")]
    return []


def check(root: str) -> List[Finding]:
    sources: Dict[str, str] = {}
    for rel in ANALYZED:
        text = read_text(root, rel)
        if text is not None:
            sources[rel] = text
    return check_texts(sources)
