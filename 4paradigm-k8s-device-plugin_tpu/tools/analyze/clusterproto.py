"""Cluster-federation protocol effect checker.

The federation plane (runtime/cluster.py) is a distributed protocol:
coordinator wire verbs (``CL_*``), the MIGRATE_OUT/MIGRATE_IN dance
against broker admin sockets, and a journaled ledger replayed through
``cluster_apply_record``.  PR 16's review hand-found five real bugs in
exactly the seams nothing machine-checks — a verb without a replay
arm, a reservation without a release, an abort arm that skips a
rollback.  This checker proves the seams against the dance grammar
declared in cluster.py's module docstring (the same
docstring-as-ground-truth pattern as the lock-order block in
runtime/server.py), per verb / message / record:

  - every ``CL_*`` constant is registered in ``CLUSTER_VERBS`` and in
    exactly one of ``CLUSTER_IDEMPOTENT_VERBS`` /
    ``CLUSTER_NONIDEMPOTENT_VERBS``;
  - every registered verb has a ``Coordinator.dispatch`` arm, at
    least one sender binding (NodeAgent / module helpers / vtpu-smi /
    the mc cluster engine / the federation traffic cell) and a
    ``verb:`` grammar row whose idempotency class matches the
    registry;
  - every journaled op (every ``{"op": ...}`` literal cluster.py
    appends) has a replay arm in ``cluster_apply_record``, a
    ``record:`` grammar row, and — via the row's ``pairs:`` /
    ``phases:`` clauses — a reserve/release pairing: a declared pair
    op must itself replay, and a record with a ``begin`` phase must
    declare (and replay) both ``commit`` and ``abort``;
  - every dance message named in the ``dance-commit:`` /
    ``dance-abort:`` sequences has a ``dance-msg:`` idempotency
    declaration consistent with runtime/protocol.py's
    ``IDEMPOTENT_VERBS`` / ``NONIDEMPOTENT_VERBS`` tables (the
    re-drive contract tools/dmc enforces dynamically).

No baseline, no suppressions: a finding fails CI until the code or
the declared grammar is fixed.
"""

from __future__ import annotations

import ast
import re
from typing import Any, Dict, List, Optional, Set, Tuple

from . import Finding, read_text, PKG_NAME

CLUSTER = f"{PKG_NAME}/runtime/cluster.py"
PROTOCOL = f"{PKG_NAME}/runtime/protocol.py"
# Where sender bindings may live (dict literals whose "kind" is a
# CL_* constant): the NodeAgent/helpers in cluster.py itself, the
# operator CLI, the mc cluster engine's canned session, and the
# federation traffic cell.
SENDER_FILES = [
    CLUSTER,
    f"{PKG_NAME}/tools/vtpu_smi.py",
    f"{PKG_NAME}/tools/mc/clustercut.py",
    "benchmarks/traffic_sim.py",
]

GT_HEADER = "cluster-dance ground truth (vtpu-analyze):"

REGISTRY = "CLUSTER_VERBS"
IDEM_REGISTRY = "CLUSTER_IDEMPOTENT_VERBS"
NONIDEM_REGISTRY = "CLUSTER_NONIDEMPOTENT_VERBS"


def _f(path: str, line: int, msg: str) -> Finding:
    return Finding("clusterproto", path, line, msg)


# -- ground truth ---------------------------------------------------------

class Grammar:
    def __init__(self) -> None:
        # verb value -> (idempotency class, journaled op or "-")
        self.verbs: Dict[str, Tuple[str, str]] = {}
        self.dances: Set[str] = set()
        # dance message name -> (idempotency class, owner)
        self.dance_msgs: Dict[str, Tuple[str, str]] = {}
        # message names appearing in dance-commit / dance-abort rows
        self.dance_seq_msgs: Set[str] = set()
        # record op -> {"owner", "pairs", "phases"}
        self.records: Dict[str, Dict[str, Any]] = {}


def parse_grammar(cluster_src: str) -> Optional[Grammar]:
    """Pull the dance grammar out of the cluster module docstring."""
    try:
        tree = ast.parse(cluster_src)
    except SyntaxError:
        return None
    doc = ast.get_docstring(tree) or ""
    if GT_HEADER not in doc:
        return None
    g = Grammar()
    block = doc.split(GT_HEADER, 1)[1]
    for raw in block.splitlines():
        line = raw.strip()
        m = re.match(r"verb:\s*(\w+)\s+(idempotent|non-idempotent)"
                     r"\s+journals:\s*(\S+)", line)
        if m:
            g.verbs[m.group(1)] = (m.group(2), m.group(3))
            continue
        m = re.match(r"dance:\s*(\w+)\s*$", line)
        if m:
            g.dances.add(m.group(1))
            continue
        m = re.match(r"dance-(?:commit|abort):\s*(.+)", line)
        if m:
            for step in m.group(1).split("->"):
                sm = re.match(r"\s*(\w+)", step)
                if sm:
                    g.dance_seq_msgs.add(sm.group(1))
            continue
        m = re.match(r"dance-msg:\s*(\w+)\s+(idempotent|non-idempotent)"
                     r"\s+owner:\s*(\w+)", line)
        if m:
            g.dance_msgs[m.group(1)] = (m.group(2), m.group(3))
            continue
        m = re.match(r"record:\s*(\w+)\s+owner:\s*(\w+)"
                     r"(?:\s+pairs:\s*(\w+))?"
                     r"(?:\s+phases:\s*(.+))?", line)
        if m:
            phases = None
            if m.group(4):
                phases = re.findall(r"\w+", m.group(4))
            g.records[m.group(1)] = {"owner": m.group(2),
                                     "pairs": m.group(3),
                                     "phases": phases}
    return g


# -- cluster.py facts -----------------------------------------------------

def _module_assigns(tree: ast.Module) -> Dict[str, ast.Assign]:
    out: Dict[str, ast.Assign] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            out[node.targets[0].id] = node
    return out


def verb_constants(tree: ast.Module) -> Dict[str, Tuple[str, int]]:
    """{constant name: (wire value, line)} for module-level CL_*."""
    out: Dict[str, Tuple[str, int]] = {}
    for name, node in _module_assigns(tree).items():
        if not name.startswith("CL_"):
            continue
        val = node.value
        if isinstance(val, ast.Constant) and isinstance(val.value, str):
            out[name] = (val.value, node.lineno)
    return out


def registry_names(tree: ast.Module, registry: str
                   ) -> Optional[Tuple[List[str], int]]:
    node = _module_assigns(tree).get(registry)
    if node is None or not isinstance(node.value,
                                      (ast.Tuple, ast.List)):
        return None
    names = [el.id for el in node.value.elts
             if isinstance(el, ast.Name)]
    return names, node.lineno


def _find_method(tree: ast.AST, cls: str, fn: str
                 ) -> Optional[ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == cls:
            for sub in node.body:
                if isinstance(sub, ast.FunctionDef) and sub.name == fn:
                    return sub
    return None


def _find_function(tree: ast.AST, fn: str) -> Optional[ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == fn:
            return node
    return None


def dispatch_arms(fn: ast.FunctionDef,
                  consts: Set[str]) -> Dict[str, int]:
    """{CL_* constant name: line} for every ``kind == CL_X``
    comparison in Coordinator.dispatch (bare-Name comparators — the
    constants live in this module, unlike the broker's ``P.X``)."""
    arms: Dict[str, int] = {}
    for node in ast.walk(fn):
        if not isinstance(node, ast.Compare):
            continue
        for part in [node.left] + list(node.comparators):
            if isinstance(part, ast.Name) and part.id in consts:
                arms.setdefault(part.id, node.lineno)
            elif isinstance(part, (ast.Tuple, ast.List)):
                for el in part.elts:
                    if isinstance(el, ast.Name) and el.id in consts:
                        arms.setdefault(el.id, node.lineno)
    return arms


def sender_bindings(src: str, consts: Set[str]) -> Set[str]:
    """CL_* constant names used as the ``"kind"`` of a sent message
    dict — matches both the bare ``CL_X`` spelling (inside
    cluster.py) and the ``CL.CL_X`` / ``cl.CL_X`` attribute spelling
    (every other sender imports the module)."""
    bound: Set[str] = set()
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return bound
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        for key, val in zip(node.keys, node.values):
            if not (isinstance(key, ast.Constant)
                    and key.value == "kind"):
                continue
            if isinstance(val, ast.Name) and val.id in consts:
                bound.add(val.id)
            elif isinstance(val, ast.Attribute) and val.attr in consts:
                bound.add(val.attr)
    return bound


def journaled_ops(tree: ast.Module) -> Dict[str, int]:
    """{op value: line} for every ``{"op": "<x>", ...}`` dict literal
    in cluster.py — the records the coordinator appends."""
    out: Dict[str, int] = {}
    apply_fn = _find_function(tree, "cluster_apply_record")
    within_apply = set()
    if apply_fn is not None:
        within_apply = {id(n) for n in ast.walk(apply_fn)}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict) or id(node) in within_apply:
            continue
        for key, val in zip(node.keys, node.values):
            if isinstance(key, ast.Constant) and key.value == "op" \
                    and isinstance(val, ast.Constant) \
                    and isinstance(val.value, str):
                out.setdefault(val.value, node.lineno)
    return out


def replay_arms(tree: ast.Module
                ) -> Tuple[Dict[str, int], Dict[str, Set[str]]]:
    """({op: line} for every ``op == "<x>"`` arm in
    cluster_apply_record, {op: phase strings compared inside that
    op's arm})."""
    fn = _find_function(tree, "cluster_apply_record")
    if fn is None:
        return {}, {}
    arms: Dict[str, int] = {}
    phases: Dict[str, Set[str]] = {}

    def _cmp_values(node: ast.Compare, var: str) -> List[str]:
        parts = [node.left] + list(node.comparators)
        if not any(isinstance(p, ast.Name) and p.id == var
                   for p in parts):
            return []
        return [p.value for p in parts
                if isinstance(p, ast.Constant)
                and isinstance(p.value, str)]

    def _walk_ifs(node: ast.AST) -> None:
        for sub in ast.walk(node):
            if not isinstance(sub, ast.If) or \
                    not isinstance(sub.test, ast.Compare):
                continue
            for op in _cmp_values(sub.test, "op"):
                arms.setdefault(op, sub.test.lineno)
                ph = phases.setdefault(op, set())
                # Scan the arm's BODY only: elif chains nest in
                # orelse, so walking the whole If would credit this
                # op with every later arm's phase comparisons.
                for stmt in sub.body:
                    for inner in ast.walk(stmt):
                        if isinstance(inner, ast.Compare):
                            ph.update(_cmp_values(inner, "phase"))
    _walk_ifs(fn)
    return arms, phases


# -- protocol.py consistency ----------------------------------------------

def protocol_idempotency(protocol_src: str
                         ) -> Tuple[Set[str], Set[str]]:
    """(idempotent wire values, non-idempotent wire values) from
    runtime/protocol.py's retry-class registries."""
    try:
        tree = ast.parse(protocol_src)
    except SyntaxError:
        return set(), set()
    consts: Dict[str, str] = {}
    regs: Dict[str, List[str]] = {}
    for name, node in _module_assigns(tree).items():
        val = node.value
        if isinstance(val, ast.Constant) and isinstance(val.value, str):
            consts[name] = val.value
        elif name in ("IDEMPOTENT_VERBS", "NONIDEMPOTENT_VERBS") and \
                isinstance(val, (ast.Tuple, ast.List)):
            regs[name] = [el.id for el in val.elts
                          if isinstance(el, ast.Name)]
    idem = {consts[n] for n in regs.get("IDEMPOTENT_VERBS", [])
            if n in consts}
    nonidem = {consts[n] for n in regs.get("NONIDEMPOTENT_VERBS", [])
               if n in consts}
    return idem, nonidem


# -- the checker ----------------------------------------------------------

def check_texts(cluster_src: str, protocol_src: str,
                senders: Dict[str, str]) -> List[Finding]:
    """Pure text-level check (tests feed fixture snippets).

    ``senders`` maps relpath -> source for every file sender bindings
    may live in; ``cluster_src`` is implicitly scanned too."""
    findings: List[Finding] = []
    try:
        tree = ast.parse(cluster_src)
    except SyntaxError as e:
        return [_f(CLUSTER, e.lineno or 1, f"syntax error: {e.msg}")]

    grammar = parse_grammar(cluster_src)
    if grammar is None:
        return [_f(CLUSTER, 1,
                   f"module docstring has no `{GT_HEADER}` block — "
                   f"the dance grammar must be declared")]

    consts = verb_constants(tree)
    const_names = set(consts)
    if not consts:
        findings.append(_f(CLUSTER, 1, "no CL_* verb constants found"))

    # -- registries: membership + idempotency partition --
    reg = registry_names(tree, REGISTRY)
    if reg is None:
        return findings + [_f(CLUSTER, 1,
                              f"verb registry {REGISTRY} is missing "
                              f"(tuple of CL_* constants)")]
    reg_names, reg_line = reg
    for name, (_value, line) in sorted(consts.items()):
        if name not in reg_names:
            findings.append(_f(CLUSTER, line,
                               f"verb {name} is not registered in "
                               f"{REGISTRY}"))
    for name in reg_names:
        if name not in const_names:
            findings.append(_f(CLUSTER, reg_line,
                               f"{REGISTRY} names unknown verb "
                               f"constant {name}"))
    idem_reg = registry_names(tree, IDEM_REGISTRY)
    nonidem_reg = registry_names(tree, NONIDEM_REGISTRY)
    idem_names = set(idem_reg[0]) if idem_reg else set()
    nonidem_names = set(nonidem_reg[0]) if nonidem_reg else set()
    if idem_reg is None or nonidem_reg is None:
        missing = [r for r, v in ((IDEM_REGISTRY, idem_reg),
                                  (NONIDEM_REGISTRY, nonidem_reg))
                   if v is None]
        findings.append(_f(CLUSTER, reg_line,
                           f"idempotency registries missing: "
                           f"{', '.join(missing)}"))
    else:
        for name in sorted(set(reg_names)
                           - (idem_names | nonidem_names)):
            findings.append(_f(
                CLUSTER, reg_line,
                f"verb {name} has no idempotency declaration "
                f"(neither {IDEM_REGISTRY} nor {NONIDEM_REGISTRY})"))
        for name in sorted(idem_names & nonidem_names):
            findings.append(_f(
                CLUSTER, reg_line,
                f"verb {name} declared BOTH idempotent and "
                f"non-idempotent"))
        for name in sorted((idem_names | nonidem_names)
                           - set(reg_names)):
            findings.append(_f(
                CLUSTER, reg_line,
                f"idempotency registries name {name} which is not "
                f"in {REGISTRY}"))

    # -- grammar rows vs registry --
    for name in reg_names:
        if name not in const_names:
            continue
        value, line = consts[name]
        row = grammar.verbs.get(value)
        if row is None:
            findings.append(_f(CLUSTER, line,
                               f"verb {name} ({value!r}) has no "
                               f"`verb:` row in the dance grammar"))
            continue
        declared = row[0]
        actual = ("idempotent" if name in idem_names else
                  "non-idempotent" if name in nonidem_names else None)
        if actual is not None and declared != actual:
            findings.append(_f(
                CLUSTER, line,
                f"verb {name}: grammar declares {declared} but the "
                f"registry says {actual}"))
    known_values = {consts[n][0] for n in reg_names
                    if n in const_names}
    for value in sorted(set(grammar.verbs) - known_values):
        findings.append(_f(CLUSTER, 1,
                           f"grammar `verb: {value}` row matches no "
                           f"registered verb constant"))

    # -- dispatch arms --
    dispatch = _find_method(tree, "Coordinator", "dispatch")
    if dispatch is None:
        findings.append(_f(CLUSTER, 1,
                           "Coordinator.dispatch not found"))
    else:
        arms = dispatch_arms(dispatch, const_names)
        for name in reg_names:
            if name in const_names and name not in arms:
                findings.append(_f(
                    CLUSTER, consts[name][1],
                    f"verb {name} has no Coordinator.dispatch arm"))

    # -- sender bindings --
    bound: Set[str] = sender_bindings(cluster_src, const_names)
    for _rel, src in sorted(senders.items()):
        bound |= sender_bindings(src, const_names)
    for name in reg_names:
        if name in const_names and name not in bound:
            findings.append(_f(
                CLUSTER, consts[name][1],
                f"verb {name} has no sender binding in "
                f"{', '.join([CLUSTER] + sorted(senders))}"))

    # -- journal records: replay arms + grammar rows + pairings --
    appended = journaled_ops(tree)
    arms_ops, arm_phases = replay_arms(tree)
    for op, line in sorted(appended.items()):
        if op not in arms_ops:
            findings.append(_f(
                CLUSTER, line,
                f"journaled op {op!r} has no replay arm in "
                f"cluster_apply_record (a crash would forget it)"))
        if op not in grammar.records:
            findings.append(_f(
                CLUSTER, line,
                f"journaled op {op!r} has no `record:` row in the "
                f"dance grammar"))
    for op in sorted(set(grammar.records) - set(appended)):
        findings.append(_f(CLUSTER, 1,
                           f"grammar `record: {op}` row matches no "
                           f"appended journal record"))
    for op, row in sorted(grammar.records.items()):
        pair = row.get("pairs")
        if pair is not None:
            if pair not in grammar.records:
                findings.append(_f(
                    CLUSTER, 1,
                    f"record {op!r} pairs with undeclared record "
                    f"{pair!r}"))
            if pair not in arms_ops:
                findings.append(_f(
                    CLUSTER, 1,
                    f"record {op!r} pairs with {pair!r} which has no "
                    f"replay arm (reserve without release)"))
        declared_phases = row.get("phases")
        if declared_phases:
            if "begin" in declared_phases:
                for need in ("commit", "abort"):
                    if need not in declared_phases:
                        findings.append(_f(
                            CLUSTER, 1,
                            f"record {op!r} declares a `begin` phase "
                            f"but no `{need}` (a reservation nobody "
                            f"can settle)"))
            have = arm_phases.get(op, set())
            for ph in declared_phases:
                if ph not in have:
                    findings.append(_f(
                        CLUSTER, 1,
                        f"record {op!r} declares phase {ph!r} with "
                        f"no replay arm for it"))
    # Every verb's declared journal op must exist.
    for value, (_cls, jop) in sorted(grammar.verbs.items()):
        if jop == "-":
            continue
        if jop not in grammar.records:
            findings.append(_f(
                CLUSTER, 1,
                f"verb {value!r} journals {jop!r} which has no "
                f"`record:` row"))
        if jop not in arms_ops:
            findings.append(_f(
                CLUSTER, 1,
                f"verb {value!r} journals {jop!r} which has no "
                f"replay arm in cluster_apply_record"))

    # -- dance messages vs protocol.py retry classes --
    for msg in sorted(grammar.dance_seq_msgs):
        if msg not in grammar.dance_msgs:
            findings.append(_f(
                CLUSTER, 1,
                f"dance message {msg!r} has no `dance-msg:` "
                f"idempotency declaration"))
    for verb in sorted(grammar.dances):
        if verb in grammar.verbs and \
                grammar.verbs[verb][0] != "non-idempotent":
            findings.append(_f(
                CLUSTER, 1,
                f"dance verb {verb!r} must be non-idempotent (each "
                f"delivery drives a fresh dance)"))
    p_idem, p_nonidem = protocol_idempotency(protocol_src)
    for msg, (cls, _owner) in sorted(grammar.dance_msgs.items()):
        if cls == "idempotent" and msg in p_nonidem:
            findings.append(_f(
                CLUSTER, 1,
                f"dance message {msg!r} declared idempotent here but "
                f"protocol.py lists it in NONIDEMPOTENT_VERBS"))
        elif cls == "idempotent" and p_idem and msg not in p_idem:
            findings.append(_f(
                CLUSTER, 1,
                f"dance message {msg!r} declared idempotent here but "
                f"protocol.py's IDEMPOTENT_VERBS does not carry it "
                f"(the client retry layer would not re-drive it)"))
        elif cls == "non-idempotent" and msg in p_idem:
            findings.append(_f(
                CLUSTER, 1,
                f"dance message {msg!r} declared non-idempotent here "
                f"but protocol.py lists it in IDEMPOTENT_VERBS"))
    return findings


def check(root: str) -> List[Finding]:
    cluster_src = read_text(root, CLUSTER)
    protocol_src = read_text(root, PROTOCOL)
    if cluster_src is None or protocol_src is None:
        return []
    senders = {}
    for rel in SENDER_FILES:
        if rel == CLUSTER:
            continue
        text = read_text(root, rel)
        if text is not None:
            senders[rel] = text
    return check_texts(cluster_src, protocol_src, senders)
