"""Wire-field exhaustiveness checker (the PR-5 protocol stragglers).

The hot-path overhaul grew the wire protocol by OPTIONAL header fields
(EXEC_BATCH ``items``, raw framing ``raw_parts``/``nbytes``, the
``lease`` reply rider).  Old clients never send them — so the serving
side must read each with a legacy-default branch (``msg.get``), and
every such field must be REGISTERED in ``protocol.py``'s
``WIRE_FIELDS`` so the contract is reviewable in one place.  This
checker proves, both directions:

  - every ``msg[...]`` / ``msg.get(...)`` / ``spec[...]`` /
    ``spec.get(...)`` field the broker reads is registered;
  - a field registered as optional-only is NEVER subscript-read (a
    subscript read of a field an old client omits kills that client's
    session on its first frame);
  - every registered field is actually read somewhere (no dead
    registry entries masking a renamed reader);
  - every verb in ``TENANT_VERBS``/``ADMIN_VERBS`` has a
    ``WIRE_FIELDS`` entry (a new verb ships with its header contract);
  - every optional REPLY rider (``REPLY_OPTIONAL_FIELDS``) is absorbed
    in ``runtime/client.py`` with ``.get`` and never subscripted.

Stdlib-only: the registries are AST-extracted from ``protocol.py``,
never imported (protocol imports msgpack; the analyze CI job installs
nothing).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from . import Finding, read_text, PKG_NAME

PROTOCOL = f"{PKG_NAME}/runtime/protocol.py"
SERVER = f"{PKG_NAME}/runtime/server.py"
CLIENT = f"{PKG_NAME}/runtime/client.py"

# Request-dict variable names in the serving code.
MSG_NAMES = ("msg", "spec")
# The dispatch discriminator every frame carries — implicitly
# registered.
IMPLICIT = ("kind",)


def _const_str(node: ast.AST, consts: Dict[str, str]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    return None


def load_registry(protocol_src: str) -> Optional[Tuple[
        Dict[str, Dict[str, Tuple[str, ...]]], Tuple[str, ...],
        Set[str]]]:
    """(WIRE_FIELDS, REPLY_OPTIONAL_FIELDS, verbs-in-verb-registries)
    extracted from protocol.py source."""
    try:
        tree = ast.parse(protocol_src)
    except SyntaxError:
        return None
    consts: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            consts[node.targets[0].id] = node.value.value
    wire: Dict[str, Dict[str, Tuple[str, ...]]] = {}
    reply: Tuple[str, ...] = ()
    verbs: Set[str] = set()
    for node in tree.body:
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        if len(targets) != 1 or not isinstance(targets[0], ast.Name):
            continue
        name = targets[0].id
        value = node.value
        if name == "WIRE_FIELDS" and isinstance(value, ast.Dict):
            for k, v in zip(value.keys, value.values):
                verb = _const_str(k, consts)
                if verb is None or not isinstance(v, ast.Dict):
                    continue
                entry: Dict[str, Tuple[str, ...]] = {"required": (),
                                                     "optional": ()}
                for kk, vv in zip(v.keys, v.values):
                    kind = _const_str(kk, consts)
                    if kind in ("required", "optional") and \
                            isinstance(vv, (ast.Tuple, ast.List)):
                        entry[kind] = tuple(
                            f for f in (_const_str(e, consts)
                                        for e in vv.elts)
                            if f is not None)
                wire[verb] = entry
        elif name == "REPLY_OPTIONAL_FIELDS" and \
                isinstance(value, (ast.Tuple, ast.List)):
            reply = tuple(f for f in (_const_str(e, consts)
                                      for e in value.elts)
                          if f is not None)
        elif name in ("TENANT_VERBS", "ADMIN_VERBS") and \
                isinstance(value, (ast.Tuple, ast.List)):
            verbs.update(v for v in (_const_str(e, consts)
                                     for e in value.elts)
                         if v is not None)
    if not wire:
        return None
    return wire, reply, verbs


def field_reads(src: str, names: Tuple[str, ...] = MSG_NAMES
                ) -> Tuple[Dict[str, int], Dict[str, int]]:
    """({field: line} subscript reads, {field: line} .get reads) of the
    request-dict variables in ``src``."""
    subs: Dict[str, int] = {}
    gets: Dict[str, int] = {}
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return subs, gets
    for node in ast.walk(tree):
        if isinstance(node, ast.Subscript) and \
                isinstance(node.ctx, ast.Load) and \
                isinstance(node.value, ast.Name) and \
                node.value.id in names and \
                isinstance(node.slice, ast.Constant) and \
                isinstance(node.slice.value, str):
            subs.setdefault(node.slice.value, node.lineno)
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "get" and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id in names and node.args and \
                isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str):
            gets.setdefault(node.args[0].value, node.lineno)
    return subs, gets


def check_texts(sources: Dict[str, str]) -> List[Finding]:
    protocol_src = sources.get(PROTOCOL)
    server_src = sources.get(SERVER)
    if protocol_src is None or server_src is None:
        return [Finding("wirefields", PROTOCOL, 1,
                        "protocol.py/server.py missing — cannot check "
                        "wire-field contract")]
    loaded = load_registry(protocol_src)
    if loaded is None:
        return [Finding("wirefields", PROTOCOL, 1,
                        "cannot locate the WIRE_FIELDS registry in "
                        "protocol.py")]
    wire, reply_fields, verbs = loaded
    findings: List[Finding] = []
    required_any: Set[str] = set(IMPLICIT)
    optional_any: Set[str] = set()
    for entry in wire.values():
        required_any.update(entry["required"])
        optional_any.update(entry["optional"])
    optional_only = optional_any - required_any
    registered = required_any | optional_any

    # Every verb the verb registries serve has a header contract.
    for verb in sorted(verbs - set(wire)):
        findings.append(Finding(
            "wirefields", PROTOCOL, 1,
            f'verb "{verb}" is in the verb registries but has no '
            f"WIRE_FIELDS entry — new verbs ship with their header "
            f"contract"))

    subs, gets = field_reads(server_src)
    for field in sorted(set(subs) - registered):
        findings.append(Finding(
            "wirefields", SERVER, subs[field],
            f'request field "{field}" is subscript-read but not in '
            f"WIRE_FIELDS — register it (required, or optional + "
            f".get)"))
    for field in sorted(set(gets) - registered):
        findings.append(Finding(
            "wirefields", SERVER, gets[field],
            f'request field "{field}" is read but not in WIRE_FIELDS '
            f"— register it"))
    for field in sorted(optional_only & set(subs)):
        findings.append(Finding(
            "wirefields", SERVER, subs[field],
            f'OPTIONAL wire field "{field}" is read by subscript — an '
            f"old client that omits it dies with KeyError; use "
            f".get with the legacy default"))
    for field in sorted(optional_any - set(gets) - set(subs)):
        findings.append(Finding(
            "wirefields", PROTOCOL, 1,
            f'optional wire field "{field}" is registered but never '
            f"read in server.py (dead entry / renamed reader)"))
    for field in sorted((required_any - set(IMPLICIT))
                        - set(subs) - set(gets)):
        findings.append(Finding(
            "wirefields", PROTOCOL, 1,
            f'required wire field "{field}" is registered but never '
            f"read in server.py (dead entry / renamed reader)"))

    # Reply riders: client must absorb each with .get, never subscript.
    client_src = sources.get(CLIENT)
    if reply_fields:
        if client_src is None:
            findings.append(Finding(
                "wirefields", CLIENT, 1,
                "client.py missing — cannot check reply riders"))
        else:
            csubs, cgets = field_reads(
                client_src, names=("resp", "reply", "lease", "msg"))
            for field in reply_fields:
                if field in csubs:
                    findings.append(Finding(
                        "wirefields", CLIENT, csubs[field],
                        f'optional reply rider "{field}" is '
                        f"subscript-read in client.py — an old "
                        f"broker's replies omit it; use .get"))
                elif field not in cgets:
                    findings.append(Finding(
                        "wirefields", CLIENT, 1,
                        f'optional reply rider "{field}" is registered '
                        f"but never absorbed in client.py"))
    return findings


def check(root: str) -> List[Finding]:
    sources: Dict[str, str] = {}
    for rel in (PROTOCOL, SERVER, CLIENT):
        text = read_text(root, rel)
        if text is not None:
            sources[rel] = text
    if PROTOCOL not in sources:
        return []
    return check_texts(sources)
