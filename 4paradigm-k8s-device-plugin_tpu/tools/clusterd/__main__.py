"""``python -m vtpu.tools.clusterd`` — run the federation
coordinator (docs/FEDERATION.md).

One coordinator per cluster (or per failure domain): it owns the
authoritative placement ledger, journaled with the same CRC-framed
machinery node brokers use, and epoch-fenced so a superseded
coordinator can never corrupt it.  Losing it is fail-static — nodes
keep serving their existing tenants; only NEW cross-node placements
wait (docs/FEDERATION.md, "coordinator loss").
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from ...runtime import cluster
from ...utils import logging as log


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="vtpu-clusterd")
    p.add_argument("--socket", default=os.environ.get(
        "VTPU_CLUSTER_SOCKET", "/usr/local/vtpu/vtpu-cluster.sock"))
    p.add_argument("--journal-dir", default=None,
                   help="placement-ledger journal dir (default: "
                        "<socket dir>/cluster-journal)")
    p.add_argument("--allocation-policy", choices=("pack", "spread"),
                   default=None,
                   help="cross-node placement policy (default pack; "
                        "also VTPU_CLUSTER_POLICY)")
    p.add_argument("--smoke", action="store_true",
                   help="run the built-in 2-node self-test and exit")
    ns = p.parse_args(argv)
    if ns.smoke:
        return cluster._smoke()  # noqa: SLF001 - canonical self-test
    journal_dir = ns.journal_dir or os.path.join(
        os.path.dirname(os.path.abspath(ns.socket)) or ".",
        "cluster-journal")
    coord = cluster.Coordinator(ns.socket, journal_dir,
                                policy=ns.allocation_policy)
    srv = coord.make_server()
    log.info("vtpu-clusterd serving on %s (policy=%s journal=%s "
             "epoch=%s)", ns.socket, coord.policy, journal_dir,
             coord.epoch)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        coord.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
