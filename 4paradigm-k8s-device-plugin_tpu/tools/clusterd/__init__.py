"""vtpu-clusterd — the federation coordinator daemon
(docs/FEDERATION.md).

Thin operational wrapper around :mod:`..runtime.cluster`: argument
parsing, journal-dir defaulting, and a serve-forever loop.  All of
the actual control plane — membership leases, the journaled
placement ledger, two-level pack|spread scoring, the cross-node
MIGRATE dance — lives in the runtime package so brokers and tests
import it without pulling in a daemon entrypoint.
"""
