"""vtpu-dmc seeded-violation selfcheck.

A distributed model checker that reports "0 violations" is only
trustworthy if a DELIBERATELY broken coordinator makes it scream.
Each seed below monkey-patches one REAL coordinator code path into a
known-bad variant — the bug classes this tool exists for, several of
them re-introductions of ordering holes the real tree has already
been fixed against — runs the explorer, and requires the named
registry row (tools/mc/invariants.py, engine ``dmc``) to fire within
the budget.  ``python -m vtpu.tools.dmc --selfcheck`` runs the matrix
(CI does); tests/test_dmc.py drives the same seeds individually.

The patches live HERE, never in the coordinator: runtime/cluster.py
stays correct, and a seed that stops firing means the CHECKER
regressed.
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, List, Tuple

from ...runtime import cluster as CL
from ...runtime import protocol as P
from ...runtime import replication as repl_mod
from . import explore


@dataclass(frozen=True)
class Seed:
    name: str
    engine: str               # always "dmc" (the registry union key)
    invariant: str            # registry row expected to fire
    scenario: str
    bug: str                  # one-line description of the injected bug
    patch: Callable[[], Any]  # contextmanager applying the broken code


# ---------------------------------------------------------------------------
# Broken placement paths
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def _seed_stale_inventory() -> Iterator[None]:
    """cluster_inventory reports every chip free (a stale cache that
    never subtracts the ledger): two placements share a chip."""
    orig = CL.cluster_inventory

    def stale(state: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
        inv: Dict[str, Dict[str, Any]] = {}
        for name, ent in (state.get("nodes") or {}).items():
            if not ent.get("alive"):
                continue
            total = int(ent.get("chips") or 0)
            inv[name] = {"free": list(range(total)), "total": total}
        return inv

    CL.cluster_inventory = stale
    try:
        yield
    finally:
        CL.cluster_inventory = orig


@contextlib.contextmanager
def _seed_reservation_blind() -> Iterator[None]:
    """free_chips forgets the in-flight migration reservations
    (state["migrating"]): the target chips of a running dance are
    handed out while the commit is on the wire."""
    orig = CL.free_chips

    def blind(state: Dict[str, Any], node: str) -> List[int]:
        ent = (state.get("nodes") or {}).get(node) or {}
        per = (state.get("used") or {}).get(node) or {}
        return [c for c in range(int(ent.get("chips") or 0))
                if str(c) not in per]   # reservations dropped

    CL.free_chips = blind
    try:
        yield
    finally:
        CL.free_chips = orig


def _place_variant(journal: bool, idempotent: bool
                   ) -> Callable[..., Dict[str, Any]]:
    """The real ``Coordinator._place`` body with one bug injected:
    ``journal=False`` acks after applying state WITHOUT the journal
    append (ack outruns durability); ``idempotent=False`` drops the
    existing-placement arm (a retried lost ack places again)."""

    def _place(self: Any, msg: Dict[str, Any]) -> Dict[str, Any]:
        tenant = str(msg["tenant"])
        size = int(msg.get("chips") or 1)
        policy = str(msg.get("policy") or self.policy)
        with self.mu:
            if idempotent:
                existing = self.state["placements"].get(tenant)
                if existing is not None:
                    ent = self.state["nodes"] \
                        .get(existing["node"]) or {}
                    return {"ok": True, "tenant": tenant,
                            "node": existing["node"],
                            "broker": ent.get("broker"),
                            "chips": list(existing["chips"]),
                            "standby": None, "existing": True}
            inv = CL.cluster_inventory(self.state)
            node, chips, _standby = CL.cluster_choose_placement(
                inv, size, policy=policy)
            if node is None:
                return {"ok": False, "code": "NO_CAPACITY",
                        "error": f"no live node has {size} "
                                 f"free chip(s)", "retry_ms": 500}
            rec = {"op": "cgrant", "tenant": tenant, "node": node,
                   "chips": chips, "hbm": msg.get("hbm")}
            if journal:
                self._append_locked(rec)
            else:
                CL.cluster_apply_record(self.state, rec)  # never
                #                                         # journaled
            broker = (self.state["nodes"].get(node) or {}) \
                .get("broker")
        return {"ok": True, "tenant": tenant, "node": node,
                "broker": broker, "chips": chips, "standby": None}

    return _place


@contextlib.contextmanager
def _seed_ack_before_journal() -> Iterator[None]:
    orig = CL.Coordinator._place
    CL.Coordinator._place = _place_variant(journal=False,
                                           idempotent=True)
    try:
        yield
    finally:
        CL.Coordinator._place = orig


@contextlib.contextmanager
def _seed_nonidempotent_place() -> Iterator[None]:
    orig = CL.Coordinator._place
    CL.Coordinator._place = _place_variant(journal=True,
                                           idempotent=False)
    try:
        yield
    finally:
        CL.Coordinator._place = orig


# ---------------------------------------------------------------------------
# Broken migration dances
# ---------------------------------------------------------------------------

def _migrate_variant(*, skip_in_abort: bool = False,
                     teardown_before_journal: bool = False,
                     skip_abort_journal: bool = False
                     ) -> Callable[..., Dict[str, Any]]:
    """The real ``Coordinator._migrate`` dance with one bug injected:

    - ``skip_in_abort`` — the abort arm forgets to discard the parked
      target copy (the orphan the resume-grace reaper exists for, but
      here it leaks on EVERY abort, not just a dropped delivery).
    - ``teardown_before_journal`` — the pre-fix ordering: the source
      teardown runs INSIDE the try before the commit is journaled, so
      a lost teardown ack aborts a dance whose source copy is already
      gone (the zero-copy window).
    - ``skip_abort_journal`` — the abort arm rolls the brokers back
      but never journals ``cmigrate abort``: the begin reservation
      leaks forever.
    """

    def _migrate(self: Any, msg: Dict[str, Any]) -> Dict[str, Any]:
        tenant = str(msg["tenant"])
        to_node = msg.get("node")
        with self.mu:
            p = self.state["placements"].get(tenant)
            if p is None:
                return {"ok": False, "code": "NOT_FOUND",
                        "error": f"tenant {tenant!r} has no cluster "
                                 f"placement"}
            src_node = p["node"]
            width = len(p.get("chips") or [])
            src_ent = self.state["nodes"].get(src_node) or {}
            inv = CL.cluster_inventory(self.state)
            inv.pop(src_node, None)
            if to_node is not None:
                inv = {k: v for k, v in inv.items()
                       if k == str(to_node)}
            node, chips, _sb = CL.cluster_choose_placement(
                inv, max(width, 1),
                policy=str(msg.get("policy") or self.policy))
            if node is None:
                return {"ok": False, "code": "NO_CAPACITY",
                        "error": "no live target node",
                        "retry_ms": 500}
            src_broker = src_ent.get("broker")
            dst_broker = (self.state["nodes"].get(node)
                          or {}).get("broker")
            self._append_locked({"op": "cmigrate", "tenant": tenant,
                                 "phase": "begin", "to_node": node,
                                 "to_chips": chips})
        try:
            out = self._admin(src_broker + ".admin",
                              {"kind": P.MIGRATE_OUT,
                               "tenant": tenant, "phase": "begin"})
            if not out.get("ok"):
                raise RuntimeError(
                    f"{out.get('code')}: {out.get('error')}")
            rin = self._admin(dst_broker + ".admin",
                              {"kind": P.MIGRATE_IN, "tenant": tenant,
                               "state": out.get("state"),
                               "blobs": out.get("blobs"),
                               "devices": chips})
            if not rin.get("ok"):
                raise RuntimeError(
                    f"{rin.get('code')}: {rin.get('error')}")
            if teardown_before_journal:
                fin = self._admin(src_broker + ".admin",
                                  {"kind": P.MIGRATE_OUT,
                                   "tenant": tenant,
                                   "phase": "commit"})
                if not fin.get("ok"):
                    raise RuntimeError(
                        f"{fin.get('code')}: {fin.get('error')}")
        except Exception as e:  # noqa: BLE001 - abort back to serving
            if not skip_in_abort:
                try:
                    self._admin(dst_broker + ".admin",
                                {"kind": P.MIGRATE_IN,
                                 "tenant": tenant, "phase": "abort"})
                except (OSError, P.ProtocolError):
                    pass
            try:
                self._admin(src_broker + ".admin",
                            {"kind": P.MIGRATE_OUT, "tenant": tenant,
                             "phase": "abort"})
            except (OSError, P.ProtocolError):
                pass
            if not skip_abort_journal:
                self._append({"op": "cmigrate", "tenant": tenant,
                              "phase": "abort"})
            return {"ok": False, "code": "MIGRATE_FAILED",
                    "error": f"{type(e).__name__}: {e}"}
        self._append({"op": "cmigrate", "tenant": tenant,
                      "phase": "commit", "to_node": node,
                      "to_chips": chips})
        if not teardown_before_journal:
            for _attempt in range(3):
                try:
                    fin = self._admin(src_broker + ".admin",
                                      {"kind": P.MIGRATE_OUT,
                                       "tenant": tenant,
                                       "phase": "commit"})
                except (OSError, P.ProtocolError):
                    continue
                if fin.get("ok"):
                    break
        return {"ok": True, "tenant": tenant, "from": src_node,
                "node": node, "broker": dst_broker, "chips": chips}

    return _migrate


@contextlib.contextmanager
def _seed_skip_abort_rollback() -> Iterator[None]:
    orig = CL.Coordinator._migrate
    CL.Coordinator._migrate = _migrate_variant(skip_in_abort=True)
    try:
        yield
    finally:
        CL.Coordinator._migrate = orig


@contextlib.contextmanager
def _seed_teardown_before_journal() -> Iterator[None]:
    orig = CL.Coordinator._migrate
    CL.Coordinator._migrate = _migrate_variant(
        teardown_before_journal=True)
    try:
        yield
    finally:
        CL.Coordinator._migrate = orig


@contextlib.contextmanager
def _seed_abort_without_journal() -> Iterator[None]:
    orig = CL.Coordinator._migrate
    CL.Coordinator._migrate = _migrate_variant(
        skip_abort_journal=True)
    try:
        yield
    finally:
        CL.Coordinator._migrate = orig


# ---------------------------------------------------------------------------
# Broken fencing
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def _seed_unfenced_coordinator() -> Iterator[None]:
    """Fence.check never refuses: a crashed-and-replaced coordinator
    keeps acking placements against a journal it no longer owns."""
    orig = repl_mod.Fence.check
    repl_mod.Fence.check = lambda self: None
    try:
        yield
    finally:
        repl_mod.Fence.check = orig


# ---------------------------------------------------------------------------
# The matrix
# ---------------------------------------------------------------------------

SEEDS: Tuple[Seed, ...] = (
    Seed("stale-inventory-double-grant", "dmc",
         "dmc-no-double-grant", "federation",
         "cluster_inventory never subtracts the ledger: two tenants "
         "are granted the same chip",
         _seed_stale_inventory),
    Seed("reservation-blind-free-chips", "dmc",
         "dmc-no-double-grant", "federation",
         "free_chips drops the in-flight migration reservations: the "
         "dance's target chips are free for the taking mid-commit",
         _seed_reservation_blind),
    Seed("migrate-skip-abort-rollback", "dmc",
         "dmc-no-orphan-copy", "federation",
         "the dance's abort arm forgets MIGRATE_IN abort: a parked "
         "target copy (journaled binds, live HBM) leaks on every "
         "aborted dance",
         _seed_skip_abort_rollback),
    Seed("place-ack-before-journal", "dmc",
         "dmc-reservation-conservation", "federation",
         "CL_PLACE acks after mutating in-memory state but before the "
         "journal append: the acked grant evaporates on coordinator "
         "crash-restart",
         _seed_ack_before_journal),
    Seed("unfenced-stale-coordinator", "dmc",
         "dmc-fenced-coordinator-never-acks", "federation",
         "the epoch fence never refuses: a replaced coordinator keeps "
         "acking placements into a journal its successor owns",
         _seed_unfenced_coordinator),
    Seed("non-idempotent-replace", "dmc",
         "dmc-re-drive-idempotence", "federation",
         "CL_PLACE drops the existing-placement arm: a client's "
         "lost-ack retry grants a second placement and strands the "
         "first",
         _seed_nonidempotent_place),
    Seed("teardown-before-commit-journal", "dmc",
         "dmc-at-least-one-full-copy", "federation",
         "the pre-fix dance ordering: source teardown before the "
         "journaled commit, so a lost teardown ack aborts the target "
         "too — zero copies cluster-wide",
         _seed_teardown_before_journal),
    Seed("abort-without-journal", "dmc",
         "dmc-reservation-conservation", "federation",
         "the abort arm rolls the brokers back but never journals "
         "cmigrate abort: the begin reservation leaks forever",
         _seed_abort_without_journal),
)


def run_seed(seed: Seed, *, max_schedules: int = 4000,
             max_faults: int = explore.DEFAULT_MAX_FAULTS
             ) -> Tuple[bool, int]:
    """Run one seed; (caught, violation_count)."""
    with seed.patch():
        stats = explore.explore_scenario(
            explore.get(seed.scenario),
            max_schedules=max_schedules, max_faults=max_faults)
    hits = [v for v in stats.violations
            if f"[{seed.invariant}]" in v]
    return bool(hits), len(stats.violations)


def run_all(*, max_schedules: int = 4000
            ) -> List[Tuple[Seed, bool, int]]:
    out = []
    for seed in SEEDS:
        caught, n = run_seed(seed, max_schedules=max_schedules)
        out.append((seed, caught, n))
    return out
