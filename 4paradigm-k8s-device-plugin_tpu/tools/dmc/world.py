"""vtpu-dmc world: the REAL federation coordinator under a simulated
lossy network.

One :class:`World` is one explored schedule: a fresh temp journal dir,
a fresh REAL :class:`~runtime.cluster.Coordinator` (never a
re-implementation — its dispatch arms, journal, fence and migration
dance run verbatim), a set of :class:`SimNode` broker models that
answer the admin MIGRATE_OUT / MIGRATE_IN contract, and a queue of
pending client messages whose delivery order and fates the explorer
decides.

Nondeterminism is ONLY the decision sequence the explorer feeds back
through ``world.choose``:

  - **top level** — for every pending message: ``deliver`` (free),
    ``dup`` (re-enqueue a copy, one fault) or ``drop`` (one fault);
    plus ``crash:coord`` (coordinator crash-restart on the same
    journal dir, one fault) and ``down:<node>`` (node death + the
    coordinator's real ``_node_down`` re-placement, one fault).
  - **admin boundary** — every ``Coordinator._admin`` call the dance
    makes is intercepted (the class staticmethod is patched for the
    schedule): ``admin:ok`` (free), ``admin:lose`` (delivered but the
    ack is lost — the classic 2PC hole, one fault) or ``admin:fail``
    (never delivered, one fault); plus ``inject:<msg>`` (free) which
    delivers another pending client message re-entrantly MID-DANCE —
    the coordinator holds no lock at its admin call sites, so this is
    exactly the concurrency the threading server allows.

Every delivery of an idempotent verb or dance message is dispatched
TWICE and the state digests compared — the re-drive-idempotence row
is checked by construction on every message, not sampled.  The other
rows drain named buckets (``World.take``) the step/terminal checks
deposit into; the registry rows live in tools/mc/invariants.py
(engine ``dmc``, phase ``net``).
"""

from __future__ import annotations

import json
import shutil
import tempfile
from typing import Any, Callable, Dict, List, Optional, Tuple

from ...runtime import cluster as CL
from ...runtime import protocol as P
from ...runtime import replication as repl_mod
from ..mc import invariants as inv_registry

# Serving states a SimNode copy can be in.  "serving" is a bound,
# executing tenant; "frozen" is a quiesced MIGRATE_OUT source copy;
# "parked" is a MIGRATE_IN target copy awaiting adoption.  All three
# are FULL copies for the at-least-one-full-copy row.
COPY_STATES = ("serving", "frozen", "parked")

# The tenant name the fence probe places after a coordinator crash —
# never collides with scenario tenants.
FENCE_PROBE_TENANT = "__dmc_fence_probe__"


class SimNode:
    """One node-local broker model: just enough of the admin
    MIGRATE_OUT / MIGRATE_IN contract (runtime/server.py) for the
    coordinator's dance to run against — faithful to the broker's
    refusal surface, because over-permissiveness here manufactures
    false zero-copy witnesses.  MIGRATE_OUT begin quiesces only a
    BOUND (serving/frozen) copy and refuses NOT_FOUND otherwise;
    commit tears down only a bound copy and no-ops when the tenant is
    gone or merely parked (mirrors ``migrate_out_finish``'s
    ``t is None`` arm — a re-driven teardown must never destroy a copy
    a LATER dance parked back here); MIGRATE_IN refuses
    MIGRATE_CONFLICT when the tenant is already bound (mirrors
    ``migrate_in_tenant``) and answers ``existing`` on a parked
    re-drive.  Chip accounting stays in the coordinator's REAL ledger;
    the SimNode only owns the copy lifecycle."""

    def __init__(self, name: str, chips: int) -> None:
        self.name = name
        self.chips = int(chips)
        self.alive = True
        self.copies: Dict[str, str] = {}   # tenant -> COPY_STATES

    def admin(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        kind = msg.get("kind")
        tenant = str(msg.get("tenant"))
        state = self.copies.get(tenant)
        if kind == P.MIGRATE_OUT:
            phase = msg.get("phase") or "begin"
            if phase == "begin":
                # Only a BOUND tenant can begin (re-drive on an
                # already-quiesced one re-acks); a parked copy is not
                # bound here (server.py migrate_out resolves through
                # state.tenants, not state.recovered).
                if state in ("serving", "frozen"):
                    self.copies[tenant] = "frozen"
                    return {"ok": True, "state": {"tenant": tenant},
                            "blobs": [], "epoch": "e1",
                            "moved_bytes": 0}
                return {"ok": False, "code": "NOT_FOUND",
                        "error": f"no bound tenant {tenant!r}"}
            if phase == "commit":
                # migrate_out_finish: tears down the BOUND tenant;
                # no-op when gone or merely parked (t is None there) —
                # a re-driven teardown must never destroy a copy a
                # LATER dance parked back onto this node.
                if state in ("serving", "frozen"):
                    self.copies.pop(tenant)
                return {"ok": True}
            if phase == "abort":
                if state == "frozen":
                    self.copies[tenant] = "serving"
                return {"ok": True}
            return {"ok": False, "code": "BAD_PHASE",
                    "error": str(phase)}
        if kind == P.MIGRATE_IN:
            if msg.get("phase") == "abort":
                if state == "parked":
                    self.copies.pop(tenant)
                    return {"ok": True}
                return {"ok": True, "noop": True}
            if state == "parked":
                # Idempotent re-drive after a lost ack.
                return {"ok": True, "existing": True}
            if state is not None:
                # server.py migrate_in_tenant: MIGRATE_CONFLICT when
                # the tenant is already bound on this node.
                return {"ok": False, "code": "MIGRATE_CONFLICT",
                        "error": f"tenant {tenant!r} already bound"}
            self.copies[tenant] = "parked"
            return {"ok": True}
        return {"ok": False, "code": "BAD_KIND", "error": str(kind)}

    def digest(self) -> str:
        return json.dumps(sorted(self.copies.items()))


class Msg:
    """One pending client message: a stable decision label plus the
    wire payload the coordinator's real dispatch receives."""

    def __init__(self, mid: str, payload: Dict[str, Any]) -> None:
        self.mid = mid
        self.payload = payload


def _state_digest(state: Dict[str, Any]) -> Dict[str, Any]:
    """Canonical ledger view for idempotence comparison: everything a
    re-delivery must leave bit-identical.  Excludes epoch/generation
    (restart-scoped) and heartbeat wall-clock bookkeeping."""
    return {
        "nodes": {n: {"alive": bool(e.get("alive")),
                      "chips": int(e.get("chips") or 0)}
                  for n, e in (state.get("nodes") or {}).items()},
        "placements": {t: {"node": p.get("node"),
                           "chips": sorted(int(c) for c in
                                           p.get("chips") or [])}
                       for t, p in
                       (state.get("placements") or {}).items()},
        "used": {n: sorted(per.items())
                 for n, per in (state.get("used") or {}).items()
                 if per},
        "migrating": {t: {"to_node": m.get("to_node"),
                          "to_chips": sorted(
                              int(c) for c in m.get("to_chips") or [])}
                      for t, m in
                      (state.get("migrating") or {}).items()},
        "totals": [int(state.get("placements_total", 0)),
                   int(state.get("migrations_total", 0))],
    }


class World:
    """One schedule's universe.  The explorer owns the decision policy
    (``choose``); the world owns mechanics, fault accounting, fate
    application and invariant-bucket deposits."""

    def __init__(self, tmp: str, *, max_faults: int,
                 choose: Callable[[List[str]], str]) -> None:
        self.tmp = tmp
        self.max_faults = max_faults
        self.faults = 0
        self.choose = choose
        self.nodes: Dict[str, SimNode] = {}
        self.pending: List[Msg] = []
        self.acked: set = set()       # tenants with an acked CL_PLACE
        self.lost: set = set()        # tenants whose data died with a node
        self.excused: set = set()     # (node, tenant) abort/teardown
        #                             # deliveries dropped by a fault:
        #                             # the resume-grace reaper owns them
        self.buckets: Dict[str, List[str]] = {}
        self.coord_seq = 0
        self.coord = self._boot_coordinator()
        self._replaced_seen = 0
        self._rejoin_seq = 0
        self._prev_admin = None

    # -- coordinator lifecycle -------------------------------------------

    def _boot_coordinator(self) -> CL.Coordinator:
        self.coord_seq += 1
        return CL.Coordinator(
            self.tmp + "/coord.sock", self.tmp + "/cl-journal",
            policy="pack", hb_dead_s=1e9)

    def __enter__(self) -> "World":
        # Patch the REAL coordinator's admin channel for this schedule:
        # every dance message routes through the simulated bus.  The
        # original is a @staticmethod, so the patch must be one too.
        self._prev_admin = CL.Coordinator.__dict__["_admin"]
        world = self

        def routed(sock_path: str, msg: Dict[str, Any],
                   timeout: float = 30.0) -> Dict[str, Any]:
            return world._admin_call(sock_path, msg)

        CL.Coordinator._admin = staticmethod(routed)
        return self

    def __exit__(self, *exc: Any) -> None:
        CL.Coordinator._admin = self._prev_admin
        try:
            self.coord.jr.close()
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass

    # -- invariant buckets ------------------------------------------------

    def deposit(self, row: str, msg: str) -> None:
        self.buckets.setdefault(row, []).append(msg)

    def take(self, row: str) -> List[str]:
        return self.buckets.pop(row, [])

    # -- fault accounting -------------------------------------------------

    def faults_left(self) -> int:
        return max(self.max_faults - self.faults, 0)

    @staticmethod
    def choice_cost(choice: str) -> int:
        head = choice.split(":", 1)[0]
        if head in ("deliver", "inject") or choice == "admin:ok":
            return 0
        return 1

    # -- digests ----------------------------------------------------------

    def digest(self) -> str:
        obj = _state_digest(self.coord.state)
        obj["copies"] = {n.name: sorted(n.copies.items())
                        for n in self.nodes.values()}
        return json.dumps(obj, sort_keys=True)

    # -- the simulated admin bus -----------------------------------------

    def _admin_call(self, sock_path: str,
                    msg: Dict[str, Any]) -> Dict[str, Any]:
        """One coordinator->broker dance message.  The explorer picks
        its fate; ``inject`` choices deliver pending CLIENT messages
        re-entrantly first (mid-dance concurrency), then the fate is
        re-asked."""
        while True:
            enabled = ["admin:ok"]
            if self.faults_left() > 0:
                enabled += ["admin:lose", "admin:fail"]
            enabled += [f"inject:{m.mid}" for m in self.pending]
            choice = self.choose(enabled)
            if choice.startswith("inject:"):
                self.deliver(choice.split(":", 1)[1])
                self.step_checks()
                continue
            break
        node = self._node_for(sock_path)
        self.faults += self.choice_cost(choice)
        if choice == "admin:fail":
            # Never delivered.  A dropped abort/teardown legitimately
            # leaves a copy behind for the resume-grace reaper — mark
            # it excused so the orphan row doesn't misfire on the
            # documented backstop path.
            if node is not None:
                kind, phase = msg.get("kind"), msg.get("phase")
                if ((kind == P.MIGRATE_IN and phase == "abort")
                        or (kind == P.MIGRATE_OUT
                            and phase == "commit")):
                    self.excused.add((node.name,
                                      str(msg.get("tenant"))))
            raise OSError("dmc: admin message dropped")
        if node is None or not node.alive:
            raise OSError(f"dmc: node for {sock_path!r} is down")
        rep = self._deliver_admin_twice(node, msg)
        self.step_checks()
        if choice == "admin:lose":
            raise OSError("dmc: admin ack lost")
        return rep

    def _node_for(self, sock_path: str) -> Optional[SimNode]:
        base = sock_path[:-len(".admin")] \
            if sock_path.endswith(".admin") else sock_path
        for node in self.nodes.values():
            if base.endswith("/" + node.name):
                return node
        return None

    def _deliver_admin_twice(self, node: SimNode,
                             msg: Dict[str, Any]) -> Dict[str, Any]:
        """Both dance messages are declared idempotent (cluster.py
        grammar + protocol.py IDEMPOTENT_VERBS): deliver every one
        twice and require bit-identical broker state — the lost-ack
        retry contract, checked by construction."""
        rep = node.admin(msg)
        d1 = node.digest()
        node.admin(dict(msg))
        d2 = node.digest()
        if d1 != d2:
            self.deposit(
                "dmc-re-drive-idempotence",
                f"dance message {msg.get('kind')}/"
                f"{msg.get('phase') or 'begin'} to {node.name!r} is "
                f"not re-drive idempotent: {d1} != {d2}")
        return rep

    # -- client-message delivery -----------------------------------------

    def _pop_pending(self, mid: str) -> Optional[Msg]:
        for i, m in enumerate(self.pending):
            if m.mid == mid:
                return self.pending.pop(i)
        return None

    def _dispatch(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        try:
            return self.coord.dispatch(dict(payload))
        except repl_mod.FencedEpoch as e:
            return {"ok": False, "code": "FENCED", "error": str(e)}
        except OSError as e:
            return {"ok": False, "code": "IO", "error": str(e)}

    def deliver(self, mid: str) -> None:
        msg = self._pop_pending(mid)
        if msg is None:
            return
        payload = msg.payload
        kind = payload.get("kind")
        rep = self._dispatch(payload)
        if kind in CL.CLUSTER_IDEMPOTENT_VERBS:
            # Idempotent verbs: re-deliver and require an identical
            # ledger — on EVERY delivery, by construction.
            d1 = self.digest()
            self._dispatch(payload)
            d2 = self.digest()
            if d1 != d2:
                self.deposit(
                    "dmc-re-drive-idempotence",
                    f"verb {kind!r} ({mid}) is not re-drive "
                    f"idempotent: ledger changed on re-delivery")
        self._client_effects(mid, payload, rep)
        self._reconcile_replaced()

    def _client_effects(self, mid: str, payload: Dict[str, Any],
                        rep: Dict[str, Any]) -> None:
        kind = payload.get("kind")
        if kind == CL.CL_PLACE and rep.get("ok"):
            tenant = str(payload["tenant"])
            self.acked.add(tenant)
            node = self.nodes.get(str(rep.get("node")))
            if node is not None and tenant not in node.copies:
                # The client binds at the granted node: a full serving
                # copy materializes there.
                node.copies[tenant] = "serving"
            self.lost.discard(tenant)
        elif kind == CL.CL_RELEASE and rep.get("ok"):
            tenant = str(payload["tenant"])
            self.acked.discard(tenant)
            self.lost.discard(tenant)
            for node in self.nodes.values():
                node.copies.pop(tenant, None)   # node-side teardown
        elif kind == CL.CL_HB and not rep.get("ok") \
                and rep.get("code") == "UNKNOWN_NODE":
            # The NodeAgent's real reaction to UNKNOWN_NODE is a
            # re-join (bounded re-dial loop): model it as a fresh
            # pending CL_JOIN.
            node = self.nodes.get(str(payload.get("node")))
            if node is not None:
                self._rejoin_seq += 1
                self.enqueue(
                    f"rejoin{self._rejoin_seq}_{node.name}",
                    {"kind": CL.CL_JOIN, "node": node.name,
                     "broker": self.tmp + "/" + node.name,
                     "chips": node.chips})
        elif kind == CL.CL_JOIN and rep.get("ok"):
            name = str(payload.get("node"))
            node = self.nodes.get(name)
            if node is None:
                # A late joiner the scenario only knew as a message:
                # materialize its broker model so placements onto it
                # can bind.
                self.nodes[name] = SimNode(
                    name, int(payload.get("chips") or 0))
            elif not node.alive:
                node.alive = True       # re-join: a fresh empty broker
                node.copies = {}

    def _reconcile_replaced(self) -> None:
        """Mirror the coordinator's node_down re-placements into the
        copy model: the tenant DATA died with the node (per-node
        journals are node-local), so the client rebinds fresh at the
        new placement — a new serving copy there; a no-capacity
        crelease just releases."""
        for ent in self.coord.replaced[self._replaced_seen:]:
            tenant = str(ent.get("tenant"))
            to = ent.get("to")
            if to is None:
                self.acked.discard(tenant)
                self.lost.discard(tenant)
            else:
                node = self.nodes.get(str(to))
                if node is not None and node.alive:
                    node.copies[tenant] = "serving"
                self.lost.discard(tenant)
        self._replaced_seen = len(self.coord.replaced)

    # -- scenario wiring --------------------------------------------------

    def add_node(self, name: str, chips: int) -> SimNode:
        node = SimNode(name, chips)
        self.nodes[name] = node
        rep = self._dispatch({"kind": CL.CL_JOIN, "node": name,
                              "broker": self.tmp + "/" + name,
                              "chips": chips})
        if not rep.get("ok"):
            raise RuntimeError(f"dmc: setup join {name!r} "
                               f"failed: {rep}")
        return node

    def place(self, tenant: str, chips: int = 1) -> None:
        """Setup-time placement (no decisions): grant + materialize."""
        rep = self._dispatch({"kind": CL.CL_PLACE, "tenant": tenant,
                              "chips": chips})
        if rep.get("ok"):
            self._client_effects("setup", {"kind": CL.CL_PLACE,
                                           "tenant": tenant}, rep)

    def enqueue(self, mid: str, payload: Dict[str, Any]) -> None:
        self.pending.append(Msg(mid, payload))

    # -- top-level fates --------------------------------------------------

    def top_enabled(self) -> List[str]:
        out: List[str] = []
        seen: set = set()
        for m in self.pending:
            if m.mid in seen:
                continue
            seen.add(m.mid)
            out.append(f"deliver:{m.mid}")
            if self.faults_left() > 0:
                out.append(f"dup:{m.mid}")
                out.append(f"drop:{m.mid}")
        if self.faults_left() > 0:
            out.append("crash:coord")
            for name, node in self.nodes.items():
                ent = (self.coord.state.get("nodes") or {}).get(name)
                if node.alive and ent is not None \
                        and ent.get("alive"):
                    out.append(f"down:{name}")
        return out

    def _adopt_parked(self) -> None:
        """Between top-level steps the client rebinds: a parked copy
        whose ledger placement is this node becomes serving (the real
        epoch-fenced resume).  Deterministic, so chained migrations of
        the same tenant stay explorable — a still-parked copy refuses
        MIGRATE_OUT begin just like the real broker."""
        placements = self.coord.state.get("placements") or {}
        for node in self.nodes.values():
            if not node.alive:
                continue
            for tenant, st in list(node.copies.items()):
                if st == "parked" and (placements.get(tenant)
                                       or {}).get("node") == node.name:
                    node.copies[tenant] = "serving"

    def apply_top(self, choice: str) -> None:
        self._adopt_parked()
        self.faults += self.choice_cost(choice)
        head, _, rest = choice.partition(":")
        if head == "deliver":
            self.deliver(rest)
        elif head == "dup":
            for m in list(self.pending):
                if m.mid == rest:
                    self.pending.append(Msg(m.mid, dict(m.payload)))
                    break
        elif head == "drop":
            self._pop_pending(rest)
        elif choice == "crash:coord":
            self.crash_coordinator()
        elif head == "down":
            self.node_down(rest)
        else:
            raise RuntimeError(f"dmc: unknown choice {choice!r}")

    def crash_coordinator(self) -> None:
        """Coordinator crash-restart on the same journal dir: the
        successor's fence claim bumps the generation, the journal
        replays, and the STALE instance is probed with a placement —
        which must refuse (fenced-coordinator-never-acks)."""
        old = self.coord
        try:
            self.coord = self._boot_coordinator()
        except Exception as e:  # noqa: BLE001 - recovery must not crash
            # Recovery refusing (or blowing up) IS a conservation
            # break: the journaled ledger failed to come back.
            self.deposit(
                "dmc-reservation-conservation",
                f"coordinator recovery failed: "
                f"{type(e).__name__}: {e}")
            self.coord = old
            return
        self._replaced_seen = len(self.coord.replaced)
        try:
            rep = old.dispatch({"kind": CL.CL_PLACE,
                                "tenant": FENCE_PROBE_TENANT,
                                "chips": 1})
            if rep.get("ok"):
                self.deposit(
                    "dmc-fenced-coordinator-never-acks",
                    "stale coordinator acked a CL_PLACE after the "
                    "successor bumped the fence generation")
        except Exception:  # noqa: BLE001 - any refusal means fenced
            pass   # refused: the fence held
        try:
            old.jr.close()
        except Exception:  # noqa: BLE001 - stale teardown best-effort
            pass
        # Every placement the old instance ACKED must survive the
        # restart (journal-before-ack): a lost one means the ack
        # outran the journal.
        placements = self.coord.state.get("placements") or {}
        for tenant in sorted(self.acked):
            if tenant not in placements:
                self.deposit(
                    "dmc-reservation-conservation",
                    f"acked placement of {tenant!r} lost across "
                    f"coordinator crash-restart (ack before journal)")
        for v in CL.check_conservation(self.coord.state):
            self.deposit("dmc-reservation-conservation",
                         f"post-restart: {v}")

    def node_down(self, name: str) -> None:
        """Node death: its copies die with it, then the REAL
        ``_node_down`` journals the death and re-places its tenants."""
        node = self.nodes[name]
        node.alive = False
        for tenant in list(node.copies):
            self.lost.add(tenant)
        node.copies = {}
        self.coord._node_down(name)
        self._reconcile_replaced()

    # -- invariant checks -------------------------------------------------

    def step_checks(self) -> None:
        """Cheap safety after every delivery and admin boundary."""
        state = self.coord.state
        for v in CL.check_conservation(state):
            row = ("dmc-no-double-grant" if "double-granted" in v
                   else "dmc-reservation-conservation")
            self.deposit(row, v)
        # Free-chip identity per live node: free + placed + reserved
        # partition the inventory exactly.
        for name, ent in (state.get("nodes") or {}).items():
            if not ent.get("alive"):
                continue
            free = set(CL.free_chips(state, name))
            used = {int(c) for c in (state.get("used") or {})
                    .get(name, {})}
            reserved: set = set()
            for m in (state.get("migrating") or {}).values():
                if isinstance(m, dict) and m.get("to_node") == name:
                    reserved.update(int(c)
                                    for c in m.get("to_chips") or [])
            total = int(ent.get("chips") or 0)
            if free & used or free & (reserved - used) \
                    or len(free | used | reserved) > total:
                self.deposit(
                    "dmc-no-double-grant",
                    f"node {name!r} chip partition broken: "
                    f"free={sorted(free)} used={sorted(used)} "
                    f"reserved={sorted(reserved)} of {total}")
        # At least one full copy somewhere alive, at EVERY step.
        placements = state.get("placements") or {}
        for tenant, p in placements.items():
            if tenant in self.lost or tenant == FENCE_PROBE_TENANT:
                continue
            if not any(node.alive and tenant in node.copies
                       for node in self.nodes.values()):
                self.deposit(
                    "dmc-at-least-one-full-copy",
                    f"tenant {tenant!r} is placed on "
                    f"{p.get('node')!r} but NO live node holds any "
                    f"copy (zero-copy window)")

    def terminal_checks(self) -> None:
        state = self.coord.state
        placements = state.get("placements") or {}
        for node in self.nodes.values():
            if not node.alive:
                continue
            for tenant in sorted(node.copies):
                placed_on = (placements.get(tenant) or {}).get("node")
                if placed_on != node.name \
                        and (node.name, tenant) not in self.excused:
                    self.deposit(
                        "dmc-no-orphan-copy",
                        f"node {node.name!r} still holds a "
                        f"{node.copies[tenant]} copy of {tenant!r} "
                        f"but the ledger places it on {placed_on!r}")
        for tenant, m in sorted((state.get("migrating") or {}).items()):
            self.deposit(
                "dmc-reservation-conservation",
                f"migration reservation for {tenant!r} -> "
                f"{(m or {}).get('to_node')!r} leaked to quiescence "
                f"(abort never journaled)")

    def collect_violations(self) -> List[str]:
        self.terminal_checks()
        return inv_registry.run_checks("dmc", "net", self)


# ---------------------------------------------------------------------------
# Scenario
# ---------------------------------------------------------------------------

def setup_federation(world: World) -> None:
    """The default scenario: two 2-chip nodes pre-joined, then a
    client workload whose every message the explorer may deliver,
    delay (by delivering others first), duplicate or drop — a
    1-chip place, a 2-chip place, a cross-node migration, a release,
    a heartbeat and a late 1-chip join."""
    world.add_node("n0", 2)
    world.add_node("n1", 2)
    world.enqueue("place_a", {"kind": CL.CL_PLACE, "tenant": "a",
                              "chips": 1})
    world.enqueue("place_b", {"kind": CL.CL_PLACE, "tenant": "b",
                              "chips": 2})
    world.enqueue("migrate_a", {"kind": CL.CL_MIGRATE, "tenant": "a"})
    world.enqueue("release_b", {"kind": CL.CL_RELEASE, "tenant": "b"})
    world.enqueue("hb_n0", {"kind": CL.CL_HB, "node": "n0"})
    world.enqueue("join_n2", {"kind": CL.CL_JOIN, "node": "n2",
                              "broker": world.tmp + "/n2",
                              "chips": 1})


def make_world(max_faults: int,
               choose: Callable[[List[str]], str]) -> Tuple[World, str]:
    tmp = tempfile.mkdtemp(prefix="vtpu-dmc-")
    world = World(tmp, max_faults=max_faults, choose=choose)
    return world, tmp


def destroy_world(world: World, tmp: str) -> None:
    shutil.rmtree(tmp, ignore_errors=True)
