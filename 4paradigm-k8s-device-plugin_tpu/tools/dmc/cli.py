"""vtpu-dmc command line — scenarios, budgets, floor gate, selfcheck.

Exploration is fully deterministic (DFS over delivery/fate decisions;
no randomness anywhere), so CI needs no seed pinning: the same tree +
the same budget flags explore the same schedules.  The CI ``dmc`` job
prints the explored-schedule counts and floor-gates them
(``--min-schedules``): a refactor that silently shrinks the explored
space — a scenario that stopped branching, a budget knob regression —
fails loudly instead of shipping a weaker checker.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

from . import explore, selfcheck


def _run_suite(ns: argparse.Namespace) -> Dict[str, Any]:
    wanted = [explore.get(ns.scenario)] if ns.scenario \
        else list(explore.SCENARIOS)
    out: Dict[str, Any] = {"scenarios": {}, "schedules": 0,
                           "decisions": 0, "violations": []}
    for scen in wanted:
        stats = explore.explore_scenario(
            scen, max_schedules=ns.max_schedules,
            max_faults=ns.max_faults, max_steps=ns.max_steps)
        out["scenarios"][scen.name] = {
            "schedules": stats.schedules,
            "decisions": stats.decisions,
            "truncated": stats.truncated,
            "violations": stats.violations,
            "witness": stats.witness,
        }
        out["schedules"] += stats.schedules
        out["decisions"] += stats.decisions
        out["violations"].extend(
            f"{scen.name}: {v}" for v in stats.violations)
    return out


def _run_selfcheck(ns: argparse.Namespace) -> int:
    results = selfcheck.run_all(max_schedules=ns.max_schedules)
    missed = [s.name for s, caught, _n in results if not caught]
    for seed, caught, n in results:
        mark = "caught" if caught else "MISSED"
        print(f"  seed {seed.name:32s} -> {seed.invariant:32s} "
              f"{mark} ({n} violation(s))")
    if missed:
        print(f"vtpu-dmc selfcheck: {len(missed)} seed(s) NOT "
              f"caught: {missed}")
        return 1
    print(f"vtpu-dmc selfcheck: all {len(results)} seeded "
          f"coordinator bugs caught")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="vtpu-dmc",
        description="distributed model checking of the cluster "
                    "federation protocol (docs/ANALYSIS.md)")
    ap.add_argument("--scenario", default=None,
                    help="run one scenario by name")
    ap.add_argument("--list", action="store_true",
                    help="list scenarios and selfcheck seeds, then "
                         "exit")
    ap.add_argument("--max-schedules", type=int, default=None,
                    help="schedule budget PER scenario "
                         "(deterministic DFS; default "
                         "VTPU_DMC_MAX_SCHEDULES or "
                         f"{explore.DEFAULT_MAX_SCHEDULES})")
    ap.add_argument("--max-faults", type=int, default=None,
                    help="network/crash fault budget per schedule "
                         "(default VTPU_DMC_MAX_FAULTS or "
                         f"{explore.DEFAULT_MAX_FAULTS}; fault-free "
                         "delivery choices are never bounded)")
    ap.add_argument("--max-steps", type=int, default=None,
                    help="top-level step cap per schedule (default "
                         "VTPU_DMC_MAX_STEPS or "
                         f"{explore.DEFAULT_MAX_STEPS})")
    ap.add_argument("--min-schedules", type=int, default=0,
                    help="fail unless the suite explored at least "
                         "this many schedules in total (CI floor "
                         "gate)")
    ap.add_argument("--selfcheck", action="store_true",
                    help="run the seeded-violation matrix instead: "
                         "every broken coordinator variant must be "
                         "caught by its invariant row")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny budget: the analyze-job wiring check, "
                         "not the real exploration")
    ap.add_argument("--json", action="store_true")
    ns = ap.parse_args(argv)

    if ns.list:
        print("scenarios:")
        for scen in explore.SCENARIOS:
            print(f"  {scen.name:16s} {scen.description}")
        print("selfcheck seeds:")
        for seed in selfcheck.SEEDS:
            print(f"  {seed.name:32s} -> {seed.invariant}")
        return 0

    if ns.smoke and ns.max_schedules is None:
        ns.max_schedules = 25

    if ns.selfcheck:
        # The seed matrix needs enough schedules to reach each bug's
        # witness; default deeper than the suite default.
        if ns.max_schedules is None:
            ns.max_schedules = 4000
        return _run_selfcheck(ns)

    if ns.max_schedules is None:
        ns.max_schedules = explore.budget_env(
            "VTPU_DMC_MAX_SCHEDULES", explore.DEFAULT_MAX_SCHEDULES)
    if ns.max_faults is None:
        ns.max_faults = explore.budget_env(
            "VTPU_DMC_MAX_FAULTS", explore.DEFAULT_MAX_FAULTS)
    if ns.max_steps is None:
        ns.max_steps = explore.budget_env(
            "VTPU_DMC_MAX_STEPS", explore.DEFAULT_MAX_STEPS)

    report = _run_suite(ns)
    if ns.json:
        print(json.dumps(report, indent=2))
    else:
        for name, s in report["scenarios"].items():
            print(f"  dmc {name:16s} schedules={s['schedules']:6d} "
                  f"decisions={s['decisions']:8d}"
                  + (f" truncated={s['truncated']}"
                     if s["truncated"] else ""))
        print(f"  dmc TOTAL: {report['schedules']} schedules, "
              f"{report['decisions']} decisions")
        for v in report["violations"]:
            print(f"VIOLATION: {v}")
        print(f"vtpu-dmc: {len(report['violations'])} violation(s)")

    if ns.min_schedules and report["schedules"] < ns.min_schedules:
        print(f"vtpu-dmc: explored-schedule FLOOR MISSED: "
              f"{report['schedules']} < --min-schedules "
              f"{ns.min_schedules} — the explored space silently "
              f"shrank", file=sys.stderr)
        return 1
    return 1 if report["violations"] else 0
