"""vtpu-dmc: distributed model checking of the cluster federation
protocol (docs/ANALYSIS.md "Distributed model checking").

The dynamic half of the federation-protocol contract — the static
half is the ``clusterproto`` checker in ``tools/analyze``.  The REAL
coordinator (``runtime/cluster.py``: dispatch arms, journal, fence,
the MIGRATE dance) runs under exhaustive network nondeterminism:
every cross-node message may be delivered, delayed past others,
duplicated or dropped, the coordinator may crash-restart (real
journal recovery + fence bump) and nodes may die mid-schedule — all
within a small CHESS-style fault budget, with DPOR sleep-set pruning
over commuting deliveries.  The ``dmc``-engine rows of the single
invariant registry (``tools/mc/invariants.py``) judge every explored
schedule: no double grant, at least one full copy, no orphan copy,
reservation conservation, fenced coordinators never ack, and
re-drive idempotence checked by construction on every message.

Run as ``python -m vtpu.tools.dmc`` or ``vtpu-smi dmc``; CI runs the
full exploration (floor-gated) plus the seeded-violation selfcheck.
"""

from __future__ import annotations

from .cli import main  # noqa: F401  (python -m vtpu.tools.dmc)
