"""vtpu-dmc explorer: DFS over network-fault schedules of the real
coordinator, with the same two prunings as the interleaving engine
(tools/mc/interleave.py):

  - **sleep sets** (DPOR-style): after exploring choice ``t`` at a
    decision node, ``t`` sleeps there; an alternative only wakes it
    when their footprints intersect (two placements share the
    inventory; a heartbeat and a placement commute; any fault/crash
    choice is conservatively dependent on everything).  Commuting
    delivery orders are explored once, not n! times.
  - **bounded faults** (the CHESS bound, re-targeted): every
    dup/drop/lose/fail/crash/down choice costs one unit of a small
    fault budget; fault-free delivery and mid-dance injection are
    free.  Most distributed-protocol bugs need one or two faults, and
    the bound turns the fate space into a dense, high-yield one.

Every schedule replays the scenario from scratch (fresh temp journal
dir, fresh REAL coordinator) following the recorded decision prefix,
then runs the default policy (cheapest choice first) to quiescence —
where the registry's ``dmc``/``net`` rows drain the world's buckets.
Exploration is fully deterministic: the only nondeterminism IS the
decision sequence, and a divergence on replay is reported as a
harness bug, never ignored.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Callable, Dict, FrozenSet, List, Optional, Tuple

from ...runtime import cluster as CL
from ...utils import logging as vlog
from . import world as W

DEFAULT_MAX_SCHEDULES = 2000
DEFAULT_MAX_FAULTS = 2
DEFAULT_MAX_STEPS = 60


def budget_env(name: str, default: int) -> int:
    """Budget knob with a VTPU_DMC_* env override (docs/FLAGS.md)."""
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class ReplayDivergence(RuntimeError):
    """A scripted choice was not enabled on replay: the world is not
    deterministic — a harness bug, reported loudly."""


Footprint = Optional[FrozenSet[Tuple[str, ...]]]


def _msg_footprint(payload: Dict) -> Footprint:
    kind = payload.get("kind")
    if kind == CL.CL_HB:
        n = str(payload.get("node"))
        return frozenset({("hb", n), ("node", n)})
    if kind == CL.CL_JOIN:
        return frozenset({("inv",), ("node", str(payload.get("node")))})
    if kind in (CL.CL_PLACE, CL.CL_RELEASE, CL.CL_MIGRATE):
        return frozenset({("inv",),
                          ("tenant", str(payload.get("tenant")))})
    if kind == CL.CL_STATUS:
        return frozenset({("status",)})
    return None


def choice_footprint(world: W.World, choice: str) -> Footprint:
    """What ledger state a choice touches.  ``None`` = unknown =
    conservatively dependent on everything (all fault/crash/admin
    choices: they reshape the reachable space)."""
    head, _, rest = choice.partition(":")
    if head in ("deliver", "dup", "drop"):
        for m in world.pending:
            if m.mid == rest:
                return _msg_footprint(m.payload)
        return None
    return None


def _dependent(fa: Footprint, fb: Footprint) -> bool:
    if fa is None or fb is None:
        return True   # unknown footprints: be conservative, stay sound
    return bool(fa & fb)


@dataclass
class Node:
    """One decision point along the current schedule."""
    enabled: List[str]
    foot: Dict[str, Footprint]
    chosen: str
    faults_before: int = 0
    tried: set = field(default_factory=set)
    sleep: set = field(default_factory=set)


@dataclass
class ScenarioStats:
    name: str = ""
    schedules: int = 0
    decisions: int = 0
    truncated: int = 0
    violations: List[str] = field(default_factory=list)
    # schedule (decision list) that produced the first violation
    witness: Optional[List[str]] = None


@dataclass
class Scenario:
    name: str
    description: str
    setup: Callable[[W.World], None]


SCENARIOS: Tuple[Scenario, ...] = (
    Scenario(
        "federation",
        "two pre-joined nodes; place/place/migrate/release/heartbeat/"
        "late-join under every delivery order, duplication, drop, "
        "coordinator crash-restart and node death within the fault "
        "budget",
        W.setup_federation),
)


def get(name: str) -> Scenario:
    for s in SCENARIOS:
        if s.name == name:
            return s
    raise KeyError(f"no dmc scenario {name!r}")


class Explorer:
    def __init__(self, scenario: Scenario, *,
                 max_schedules: int = DEFAULT_MAX_SCHEDULES,
                 max_faults: int = DEFAULT_MAX_FAULTS,
                 max_steps: int = DEFAULT_MAX_STEPS) -> None:
        self.scenario = scenario
        self.max_schedules = max_schedules
        self.max_faults = max_faults
        self.max_steps = max_steps
        self.stats = ScenarioStats(name=scenario.name)

    # -- one schedule ------------------------------------------------------

    def _run_once(self, script: List[str],
                  nodes: List[Node]) -> List[str]:
        """Execute the scenario following ``script``; extend ``nodes``
        with the decision points actually taken (prefix nodes are
        reused, fresh ones appended)."""
        step_box = [0]
        world_box: List[Optional[W.World]] = [None]

        def choose(enabled: List[str]) -> str:
            self.stats.decisions += 1
            step = step_box[0]
            step_box[0] += 1
            ids = sorted(enabled)
            world = world_box[0]
            foot = {c: choice_footprint(world, c) for c in ids}
            if step < len(nodes):
                node = nodes[step]
                if node.chosen not in ids:
                    raise ReplayDivergence(
                        f"{self.scenario.name}: step {step} scripted "
                        f"choice {node.chosen!r} not enabled "
                        f"(enabled={ids})")
                node.enabled = ids
                node.foot = foot
                return node.chosen
            # Past the script: default policy (cheapest choice
            # first), recorded as a fresh node.
            parent = nodes[-1] if nodes else None
            sleep: set = set()
            if parent is not None:
                chosen_foot = parent.foot.get(parent.chosen)
                sleep = {
                    c for c in parent.sleep | (parent.tried
                                               - {parent.chosen})
                    if c in foot and not _dependent(
                        foot.get(c), chosen_foot)}
            free = [c for c in ids if W.World.choice_cost(c) == 0]
            pick = free[0] if free else ids[0]
            if pick in sleep:
                awake = [c for c in ids if c not in sleep]
                awake_free = [c for c in awake
                              if W.World.choice_cost(c) == 0]
                if awake_free:
                    pick = awake_free[0]
                elif awake:
                    pick = awake[0]
            node = Node(enabled=ids, foot=foot, chosen=pick,
                        faults_before=world.faults)
            node.tried.add(pick)
            node.sleep = sleep
            nodes.append(node)
            return pick

        world, tmp = W.make_world(self.max_faults, choose)
        world_box[0] = world
        violations: List[str] = []
        truncated = False
        try:
            with world:
                self.scenario.setup(world)
                world.step_checks()
                top_steps = 0
                while world.pending:
                    if top_steps >= self.max_steps:
                        truncated = True
                        break
                    enabled = world.top_enabled()
                    choice = choose(enabled)
                    world.apply_top(choice)
                    world.step_checks()
                    top_steps += 1
                if truncated:
                    self.stats.truncated += 1
                else:
                    violations.extend(world.collect_violations())
        finally:
            W.destroy_world(world, tmp)
        return violations

    # -- DFS over schedules ------------------------------------------------

    def explore(self) -> ScenarioStats:
        # Thousands of schedules re-run the coordinator's node_down /
        # takeover paths on purpose; their operator warnings are
        # noise here.  Errors still print.
        prev_level = vlog._cached_level
        vlog._cached_level = vlog.LEVEL_ERROR
        try:
            return self._explore()
        finally:
            vlog._cached_level = prev_level

    def _explore(self) -> ScenarioStats:
        nodes: List[Node] = []
        script: List[str] = []
        while True:
            try:
                violations = self._run_once(script, nodes)
            except ReplayDivergence as e:
                self.stats.violations.append(f"[determinism] {e}")
                self.stats.witness = list(script)
                break
            self.stats.schedules += 1
            if violations:
                self.stats.violations.extend(violations)
                self.stats.witness = [n.chosen for n in nodes]
                break
            if self.stats.schedules >= self.max_schedules:
                break
            # Backtrack: deepest node with an unexplored, awake,
            # budget-feasible alternative.
            nxt = None
            while nodes:
                node = nodes[-1]
                feasible = [
                    c for c in node.enabled
                    if c not in node.tried and c not in node.sleep
                    and node.faults_before + W.World.choice_cost(c)
                    <= self.max_faults]
                if feasible:
                    c = feasible[0]
                    node.tried.add(c)
                    new = Node(enabled=node.enabled, foot=node.foot,
                               chosen=c,
                               faults_before=node.faults_before)
                    new.tried = node.tried  # shared explored set
                    new.sleep = set(node.sleep)
                    nodes[-1] = new
                    nxt = [n.chosen for n in nodes]
                    break
                nodes.pop()
            if nxt is None:
                break  # space exhausted
            script = nxt
            nodes = nodes[:len(script)]
            for n in nodes:
                n.foot = dict(n.foot)
        return self.stats


def explore_scenario(scenario: Scenario, **kw) -> ScenarioStats:
    return Explorer(scenario, **kw).explore()
