"""vtpu-chaos CLI: the churn suite, its smoke check, and the tenant
child entry point.

  python -m vtpu.tools.chaos --quick --seeds 1,2,3,4,5 --random-extra
  python -m vtpu.tools.chaos --smoke        # = vtpu-smi chaos --smoke

The suite exits non-zero on ANY invariant violation in ANY schedule;
every schedule's seed is printed so a failure replays exactly
(docs/CHAOS.md)."""

from __future__ import annotations

import argparse
import json
import random
import sys
import time
from typing import List, Optional


def _smoke() -> List[str]:
    """Dependency-light wiring check (no jax, no subprocesses): fault
    grammar + seeded determinism, jittered-backoff spread, degraded
    local enforcement, and the retry-set derivation.  Runs in the
    analyze CI job."""
    from ...runtime import faults as F
    errs: List[str] = []

    # Grammar: the documented examples must parse; junk must not.
    for spec in ("sock_drop@EXEC_BATCH:p=0.01;"
                 "sigkill_broker@dispatch:after=500",
                 "fsync_eio@journal:nth=3;reply_delay@GET:ms=50"):
        try:
            F.FaultPlan(spec, seed=7)
        except F.FaultSpecError as e:
            errs.append(f"documented spec failed to parse: {e}")
    for bad in ("nosite", "x@y:zap=1", "x@y:p=high"):
        try:
            F.FaultPlan(bad, seed=0)
            errs.append(f"junk spec {bad!r} parsed")
        except F.FaultSpecError:
            pass

    # Determinism: same spec + seed -> identical fire schedule.
    def schedule(seed: int) -> List[bool]:
        plan = F.FaultPlan("sock_drop@recv:p=0.1", seed=seed)
        pt = plan.points[0]
        return [pt.should_fire() for _ in range(500)]

    if schedule(3) != schedule(3):
        errs.append("same seed produced different fault schedules")
    if schedule(3) == schedule(4):
        errs.append("different seeds produced identical schedules "
                    "(rng not seeded per plan)")
    nth = F.FaultPlan("fsync_eio@journal:nth=3", seed=0).points[0]
    fired = [nth.should_fire() for _ in range(5)]
    if fired != [False, False, True, False, False]:
        errs.append(f"nth trigger wrong: {fired}")

    # Reconnect stampede: 16 tenants' jittered schedules must spread.
    from ...runtime.client import full_jitter_delay
    delays = []
    for i in range(16):
        rng = random.Random(f"tenant-{i}\x000")
        delays.append(full_jitter_delay(rng, 0.05, 2.0, 4))
    buckets = {int(d / 0.05) for d in delays}
    if len(buckets) < 8:
        errs.append(f"16 tenants' backoff delays landed in only "
                    f"{len(buckets)} 50ms buckets (stampede risk)")
    if max(delays) > 0.8 + 1e-9:
        errs.append("full-jitter delay exceeded its cap")

    # Degraded-mode local enforcement (mirror backend — no region).
    from ...runtime.degraded import LocalEnforcer
    enf = LocalEnforcer(hbm_limit=1000, core_pct=50, used_bytes=900)
    if not enf.admit_bytes(100):
        errs.append("degraded enforcer refused a within-quota PUT")
    if enf.admit_bytes(101):
        errs.append("degraded enforcer admitted an over-quota PUT "
                    "(NOT fail-closed)")
    drained = 0
    while enf.admit_us(50_000) and drained < 100:
        drained += 1
    if drained >= 100:
        errs.append("degraded rate bucket never exhausted (rate quota "
                    "does not bite)")

    # Retry-set derivation: the client's transparent-retry kinds come
    # from the protocol registry and can never contain execute verbs.
    from ...runtime import protocol as P
    from ...runtime.client import RuntimeClient
    kinds = RuntimeClient._RESUME_RETRY_KINDS
    if not kinds or P.EXECUTE in kinds or P.EXEC_BATCH in kinds:
        errs.append(f"retry-kind derivation broken: {sorted(kinds)}")

    # Preemption policy (docs/SCHEDULING.md): the pure decision
    # function the churn schedule's park-then-kill scenario rides on.
    # Sustained priority-0 demand must pick the busiest lower-priority
    # victim; same-priority load, un-sustained demand, and a
    # victimless chip must all decline.
    from ...runtime.server import preempt_decision
    pick = preempt_decision(
        [("hi", 0, 1.0, 4), ("lo1", 1, 1.0, 2), ("lo2", 1, 0.0, 9)],
        now=2.0, after_ms=250.0)
    if pick != ("hi", "lo2"):
        errs.append(f"preempt_decision missed the busiest lower-"
                    f"priority victim: {pick}")
    if preempt_decision([("hi", 0, 1.9, 4), ("lo", 1, 1.0, 2)],
                        now=2.0, after_ms=250.0) is not None:
        errs.append("preempt_decision fired on UN-sustained demand")
    if preempt_decision([("a", 1, 1.0, 4), ("b", 1, 1.0, 4)],
                        now=2.0, after_ms=250.0) is not None:
        errs.append("preempt_decision fired without a lower-priority "
                    "victim")
    if preempt_decision([("hi", 0, 1.0, 4), ("idle", 1, 0.0, 0)],
                        now=2.0, after_ms=250.0) is not None:
        errs.append("preempt_decision picked a loadless victim")

    # Overload shedding: lowest priority first, priority 0 only at the
    # hard cap, burn-hot halves the lower tiers' thresholds.
    from ...runtime.server import AdmissionState
    adm = AdmissionState()
    if not (adm.shed_fraction(0) == 1.0
            and adm.shed_fraction(1) < 1.0
            and adm.shed_fraction(2) <= adm.shed_fraction(1)):
        errs.append("shed fractions are not priority-ordered")
    cold = adm.shed_fraction(1)
    adm.burn_hot = True
    if not adm.shed_fraction(1) < cold:
        errs.append("burn-hot did not tighten the priority-1 shed "
                    "threshold")
    if adm.shed_fraction(0) != 1.0:
        errs.append("burn-hot must never lower the priority-0 "
                    "threshold below the hard cap")
    return errs


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="vtpu-chaos", description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="cheap wiring check (no jax, no processes)")
    ap.add_argument("--seeds", default="1,2,3,4,5",
                    help="comma-separated fixed schedule seeds")
    ap.add_argument("--random-extra", action="store_true",
                    help="append one randomized seed (printed for "
                         "repro)")
    ap.add_argument("--tenants", type=int, default=4)
    ap.add_argument("--quick", action="store_true",
                    help="short windows (CI)")
    ap.add_argument("--failover", action="store_true",
                    help="failover cells instead of respawn churn: "
                         "kill -9 the PRIMARY with a live hot standby "
                         "and gate per-tenant blackout p99 against "
                         "the load-scaled 1s budget + the respawn "
                         "baseline measured in the same run "
                         "(docs/FAILOVER.md)")
    ap.add_argument("--no-control", action="store_true",
                    help="skip the no-fault control cell (strict "
                         "fixed thresholds; the default scales them "
                         "by the machine's measured load factor)")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--out", default=None, metavar="FILE")
    # tenant child plumbing (spawned by the driver)
    ap.add_argument("--tenant-child", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--socket", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--name", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--progress", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--duration", type=float, default=10.0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--child-seed", type=int, default=0, dest="seed",
                    help=argparse.SUPPRESS)
    ap.add_argument("--child-priority", type=int, default=1,
                    dest="priority", help=argparse.SUPPRESS)
    ap.add_argument("--hbm", type=int, default=0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--core", type=int, default=0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--child-fastlane", action="store_true",
                    dest="fastlane", help=argparse.SUPPRESS)
    ap.add_argument("--child-devices", default="", dest="devices",
                    help=argparse.SUPPRESS)
    ns = ap.parse_args(argv)

    if ns.tenant_child:
        from .tenant import tenant_main
        return tenant_main(ns)

    if ns.smoke:
        errs = _smoke()
        out = {"smoke": "vtpu-chaos", "ok": not errs, "errors": errs}
        print(json.dumps(out, indent=2 if not ns.json else None))
        return 0 if not errs else 1

    from .driver import measure_control, run_schedule
    seeds = [int(s) for s in ns.seeds.split(",") if s.strip()]
    if ns.random_extra:
        extra = random.SystemRandom().randrange(1, 10**6)
        print(f"[chaos] randomized extra seed: {extra} "
              f"(replay with --seeds {extra})", file=sys.stderr)
        seeds.append(extra)
    suite = ("vtpu-chaos failover" if ns.failover
             else "vtpu-chaos churn")
    report = {"suite": suite, "tenants": ns.tenants,
              "quick": bool(ns.quick), "schedules": []}
    ok = True
    for seed in seeds:
        t0 = time.monotonic()
        print(f"[chaos] schedule seed={seed} ...", file=sys.stderr)
        slog = lambda m: print(m, file=sys.stderr)  # noqa: E731
        if ns.failover:
            from .failover import run_failover
            factor = 1.0
            ctl = None
            if not ns.no_control:
                ctl = measure_control(seed, tenants=ns.tenants,
                                      quick=ns.quick, log=slog)
                factor = float(ctl.get("factor", 1.0))
            res = run_failover(seed, tenants=ns.tenants,
                               quick=ns.quick, log=slog,
                               load_factor=factor)
            if ctl is not None:
                res["control"] = ctl
            print(f"[chaos]   seed={seed} ok={res['ok']} "
                  f"blackout_p99={res.get('blackout_p99_ms')}ms "
                  f"respawn={res.get('respawn_baseline_ms')}ms "
                  f"leak={res.get('region_leak_bytes')}B",
                  file=sys.stderr)
        else:
            res = run_schedule(seed, tenants=ns.tenants,
                               quick=ns.quick, log=slog,
                               control=not ns.no_control)
            print(f"[chaos]   seed={seed} ok={res['ok']} "
                  f"recovery_ms={res.get('recovery_ms')} "
                  f"ratio={res.get('recovery_ratio')} "
                  f"leak={res.get('region_leak_bytes')}B",
                  file=sys.stderr)
        res["wall_s"] = round(time.monotonic() - t0, 1)
        report["schedules"].append(res)
        ok = ok and res["ok"]
        for v in res["violations"]:
            print(f"[chaos]   VIOLATION {v}", file=sys.stderr)
    report["ok"] = ok
    text = json.dumps(report, indent=None if ns.json else 2)
    if ns.out:
        with open(ns.out, "w") as f:
            f.write(text + "\n")
    print(text if ns.json else
          json.dumps({"suite": suite, "ok": ok,
                      "schedules": len(report["schedules"])}))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
