"""Churn-suite tenant child: one REAL tenant process under fault fire.

Runs the serving-loop shape the broker optimizes for — pipelined
EXEC_BATCH executes with zero-round-trip frees, periodic in-flight
PUTs — and SURVIVES whatever the schedule throws at it: connection
drops reconnect (full-jitter backoff), a SIGKILLed broker's successor
is re-adopted via HELLO epoch resume, a fresh epoch triggers
re-put/re-compile.  Progress (wall time + step count) streams to a
file the driver reads to measure pre/post-crash throughput and
recovery time; the final stdout line carries the child's own verdicts
(resume count, state losses, the reply-durability probe)."""

from __future__ import annotations

import json
import os
import random
import time
from typing import Any, Dict


def tenant_main(ns) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from ...runtime.client import (RuntimeClient, RuntimeError_,
                                   VtpuConnectionLost, VtpuStateLost)

    rng = random.Random(ns.seed)
    report: Dict[str, Any] = {
        "tenant": ns.name, "steps": 0, "resumes": 0, "state_lost": 0,
        "rebind_races": 0, "reconnects": 0, "errors": 0, "puts": 0,
        "durability_ok": True, "durability_checks": 0,
    }
    progress = open(ns.progress, "w", buffering=1)

    def mark() -> None:
        progress.write(f"{time.time():.6f} {report['steps']}\n")

    # The broker may still be booting (or mid-respawn): bounded dial
    # loop, jittered like the client's own backoff.
    deadline = time.monotonic() + 30.0
    client = None
    # Multi-chip fastlane churn (vtpu-fastlane-everywhere): the driver
    # may grant this child several chips so the kill -9 lands
    # mid-SHARDED-flight (per-chip rings + completion-vector join).
    devices = [int(d) for d in ns.devices.split(",") if d.strip()] \
        if getattr(ns, "devices", "") else None
    while client is None:
        try:
            client = RuntimeClient(ns.socket, tenant=ns.name,
                                   priority=ns.priority,
                                   devices=devices,
                                   hbm_limit=ns.hbm or None,
                                   core_limit=ns.core or None)
        except (OSError, RuntimeError_):
            if time.monotonic() > deadline:
                raise
            time.sleep(0.1 + 0.2 * rng.random())

    probe = (np.arange(64, dtype=np.float32) * (1.0 + ns.seed))
    x = np.random.default_rng(ns.seed).random(256).astype(np.float32)

    def setup() -> str:
        """(Re-)establish device state; returns the executable id."""
        client.put(probe, "probe")
        client.put(x, "x0")
        exe = client.compile(lambda a: a * 1.0001 + 1.0, [x])
        return exe.id

    def check_probe() -> None:
        """Reply-durability on the live system: the acked probe PUT
        must read back bit-identical after a kill -9 resume.  A probe
        that cannot be fetched at all (connection died again mid-check)
        is retried on the next resume, not a verdict."""
        try:
            got = client.get("probe")
        except (RuntimeError_, OSError):
            return
        report["durability_checks"] += 1
        if not np.array_equal(got, probe):
            report["durability_ok"] = False

    def setup_retry() -> str:
        """setup() that shrugs off crashes mid-rebuild (the schedule
        may kill the broker while we are re-putting)."""
        deadline = time.monotonic() + 60.0
        while True:
            try:
                return setup()
            except (VtpuStateLost, VtpuConnectionLost):
                continue
            except (RuntimeError_, OSError):
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.05 + 0.1 * rng.random())

    exe_id = setup_retry()
    # Fastlane child (VTPU_FASTLANE=1 in the env): dispatch-time frees
    # force the brokered fallback, so the ring-eligible loop relies on
    # out-id overwrite semantics instead — the 256-id cycle bounds
    # memory exactly like the free list did.  The window is kept SMALL
    # and the loop paced: an unpaced ring loop runs ~10x the brokered
    # children and would starve the respawn/resume window the churn
    # verdicts time (the suite judges invariants, not throughput).
    use_free = not ns.fastlane
    window = 8 if ns.fastlane else 32
    outstanding = 0
    prev_out = None
    seq = 0
    t_end = time.monotonic() + ns.duration
    last_mark = 0.0
    while time.monotonic() < t_end:
        try:
            while outstanding < window and time.monotonic() < t_end:
                oid = f"y{seq & 255}"
                free = (prev_out,) if (prev_out and use_free) else ()
                client.execute_send_ids(exe_id, ["x0"], [oid],
                                        free=free)
                prev_out = oid
                seq += 1
                outstanding += 1
                if rng.random() < 0.02:
                    # In-flight PUT riding the pipeline (the VERDICT
                    # #8 scenario wants PUTs airborne at the kill).
                    client.put_send(x, "x0")
                    outstanding += client.put_parts(x)
                    report["puts"] += 1
            while outstanding > window // 2:
                client.recv_reply()
                outstanding -= 1
                report["steps"] += 1
            now = time.monotonic()
            if now - last_mark > 0.05:
                last_mark = now
                mark()
            if ns.fastlane:
                time.sleep(0.002)  # pace the ring loop (see window)
        except VtpuStateLost as e:
            # SAME-epoch state loss is the documented single-connection
            # teardown race (an injected client-side drop let teardown
            # beat the rebind — the broker never died); the epoch-
            # resume invariant judges only CROSS-epoch loss, where the
            # journal resume genuinely failed.
            if e.epoch_old == e.epoch_new:
                report["rebind_races"] += 1
            else:
                report["state_lost"] += 1
            outstanding = 0
            prev_out = None
            exe_id = setup_retry()
        except VtpuConnectionLost as e:
            # Same tenant state, in-flight replies lost: restart the
            # send/recv pairing.  resumed=True is the journal-resume
            # path the churn suite exists to prove.
            report["reconnects"] += 1
            if getattr(e, "resumed", False):
                report["resumes"] += 1
                check_probe()
            outstanding = 0
            prev_out = None
        except RuntimeError_ as e:
            # Typed request failure (injected INTERNAL, NOT_FOUND of a
            # purged out-id, ...): note it, resync the pipeline state
            # and keep going — a chaos tenant never gives up.
            report["errors"] += 1
            report["last_error"] = f"{type(e).__name__}: {e}"
            outstanding = 0
            prev_out = None
            try:
                client.stats()
            except (RuntimeError_, OSError):
                time.sleep(0.05)
    # Drain + drop everything so the broker-side teardown leaves ZERO
    # ledger bytes behind (the quota-leak assertion reads the region
    # after every child exits).
    try:
        client.stats()
        check_probe()
        client.delete_many(["probe", "x0"]
                           + [f"y{i}" for i in range(256)])
    except (RuntimeError_, OSError):
        pass
    mark()
    try:
        client.close()
    except OSError:
        pass
    print("TENANT_RESULT " + json.dumps(report), flush=True)
    return 0
