"""Churn-schedule driver: real broker, real tenants, real kill -9.

One schedule (= one seed) is the unified churn scenario VERDICT #8
asked for:

  1. spawn a journal-enabled broker SUBPROCESS (``python -m
     vtpu.runtime.server``) and 4+ tenant SUBPROCESSES (tenant.py)
     running pipelined EXEC_BATCH loops with in-flight PUTs and live
     rate leases (core-metered broker, leases on by default);
  2. measure steady pre-crash throughput, then ``SIGKILL`` the broker
     mid-flight and respawn it — the successor replays the journal
     and every tenant re-adopts its state via HELLO epoch resume;
  3. measure recovery time + post-crash throughput, let the tenants
     drain and exit, then hold the LIVE system to the PR 6 invariant
     registry's churn rows:

       hbm-ledger-balance   every region slot reads ZERO bytes after
                            teardown (quota leak == 0)
       lease-nonnegative    no STATS poll ever saw a negative lease
       token-conservation   no lease ever exceeded the one-quantum
                            clamp, and teardown refunded them all
       reply-durability     each tenant's acked probe PUT read back
                            bit-identical after the kill -9 resume
       epoch-resume         every tenant resumed (no state loss)
       throughput-recovery  post-crash >= RECOVERY_RATIO x pre-crash

Determinism: the seed fixes the kill fraction, the per-seed
``VTPU_FAULTS`` garnish (connection drops, a torn journal write) and
every tenant's RNG; CI runs 5 fixed seeds plus one randomized seed
whose value is PRINTED so any failure replays exactly.
"""

from __future__ import annotations

import json
import os
import random
import signal
import socket as socketmod
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

PKG_DIR = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
REPO = os.path.dirname(PKG_DIR)

# Acceptance floor: post-crash steady-state throughput vs pre-crash.
RECOVERY_RATIO = 0.9
# One scheduler quantum (µs) — the broker-side lease clamp the
# token-conservation live check holds STATS to.
LEASE_CLAMP_US = 100_000
# Burst-credit cap the live credit check holds STATS to (the broker
# default: VTPU_BURST_CAP_QUANTA=20 quanta of 100ms).
CREDIT_CAP_US = 20 * LEASE_CLAMP_US


def _seed_faults(seed: int) -> Tuple[str, str]:
    """(broker VTPU_FAULTS, tenant VTPU_FAULTS) for one schedule —
    deterministic garnish on top of the SIGKILL every schedule gets.
    Kept mild: the schedule must still reach steady state to measure
    recovery against."""
    broker = ""
    tenant = ""
    if seed % 3 == 1:
        # One torn journal write mid-run: the append fails typed, the
        # log self-repairs to the record boundary, recovery still
        # resumes every tenant.
        broker = "write_short@journal:nth=40"
    elif seed % 3 == 2:
        # Sporadic client-side connection drops: the reconnect path
        # (full-jitter backoff, idempotent retry) runs during steady
        # state, not just at the kill.
        tenant = "sock_drop@recv:p=0.001"
    return broker, tenant


class Schedule:
    """Everything one churn run needs, derived from its seed."""

    def __init__(self, seed: int, tenants: int, quick: bool):
        rng = random.Random(seed)
        self.seed = seed
        self.tenants = max(int(tenants), 4)
        self.duration = 12.0 if quick else 18.0
        # Kill lands mid-steady-state (after every child's jax import
        # + compile ramp), varied per seed so the cut point sweeps the
        # pipeline phases across the suite.
        self.kill_at = (5.0 if quick else 6.5) + rng.random() * 1.0
        self.broker_faults, self.tenant_faults = _seed_faults(seed)
        # vtpu-elastic: tenant 0 runs at priority 0 (the floor-
        # demanding class), the rest at 1 — under saturation the
        # broker's preemption policy must park a low-priority tenant,
        # and the kill -9 is preferentially timed to land while one is
        # PARKED (the preempted-mid-suspend crash the suspend journal
        # records must survive).
        self.priorities = [0 if i == 0 else 1
                           for i in range(self.tenants)]


def _wait_socket(path: str, timeout: float) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            s = socketmod.socket(socketmod.AF_UNIX,
                                 socketmod.SOCK_STREAM)
            s.settimeout(1.0)
            try:
                s.connect(path)
                return True
            except OSError:
                pass
            finally:
                s.close()
        time.sleep(0.05)
    return False


def _admin_stats(sock: str) -> Optional[dict]:
    from ...runtime import protocol as P
    s = socketmod.socket(socketmod.AF_UNIX, socketmod.SOCK_STREAM)
    s.settimeout(2.0)
    try:
        s.connect(sock + ".admin")
        P.send_msg(s, {"kind": P.STATS})
        return P.recv_msg(s)
    except OSError:
        return None
    finally:
        s.close()


def _admin_slo(sock: str) -> Optional[dict]:
    """One SLO-plane read over the admin socket (docs/OBSERVABILITY.md)
    — the churn suite's attainment/sketch timeline source."""
    from ...runtime import protocol as P
    s = socketmod.socket(socketmod.AF_UNIX, socketmod.SOCK_STREAM)
    s.settimeout(2.0)
    try:
        s.connect(sock + ".admin")
        P.send_msg(s, {"kind": P.SLO})
        return P.recv_msg(s)
    except OSError:
        return None
    finally:
        s.close()


class ChurnRun:
    """One schedule's execution + live-invariant verdicts.

    ``floor_scale`` (load-awareness, docs/CHAOS.md): the recovery-time
    and throughput-recovery thresholds are scaled by the no-fault
    CONTROL cell's measured stability on this machine, so a CI red
    means a regression — not a busy runner (the UNCHANGED baseline was
    observed failing 1/5 under load before this existed)."""

    def __init__(self, sched: Schedule, workdir: Optional[str] = None,
                 log=print, floor_scale: float = 1.0):
        self.floor_scale = max(min(float(floor_scale), 1.0), 0.25)
        self.sched = sched
        self.tmp = workdir or tempfile.mkdtemp(
            prefix=f"vtpu-chaos-s{sched.seed}-")
        self.sock = os.path.join(self.tmp, "chaos.sock")
        self.jdir = os.path.join(self.tmp, "journal")
        self.log = log
        self.broker: Optional[subprocess.Popen] = None
        self.broker_log = open(os.path.join(self.tmp, "broker.log"),
                               "ab")
        self.polls: List[dict] = []
        # SLO-impact timeline: (wall ts, {tenant: {count, attainment}})
        # samples across the churn — before / during / after the kill.
        self.slo_polls: List[dict] = []
        self.violations: List[str] = []
        # vtpu-elastic live evidence: every poll instant at which some
        # tenant was observed preemption-PARKED (the preferred kill
        # window), and the preemption counters' running max.
        self.parked_seen: List[float] = []
        self.max_preemptions = 0
        # vtpu-fastlane churn coverage (docs/PERF.md): tenant 1 (when
        # present) rides the interposer-only data plane; its lane's
        # ring_steps counter is sampled live so the verdict can prove
        # the ring was HOT at the kill and resumed after it.
        self.fastlane_idx = 1 if sched.tenants > 1 else -1
        self.fastlane_polls: List[Tuple[float, int]] = []

    # -- processes ---------------------------------------------------------

    def _broker_env(self) -> dict:
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
            "VTPU_JOURNAL_DIR": self.jdir,
            "VTPU_LEASE_SIDECAR": os.path.join(self.tmp, "lease.json"),
            "VTPU_LOG_LEVEL": "0",
            "VTPU_TRACE": "0",
            # Frequent SLO sketch journaling (docs/OBSERVABILITY.md):
            # the churn verdict asserts attainment history SURVIVES the
            # kill -9 resume without double-counting in-flight work, so
            # the journaled state must lag the kill by ~a keeper tick.
            "VTPU_SLO_JOURNAL_S": "0.5",
            # Quick preemption engagement (docs/SCHEDULING.md): the
            # priority-0 tenant's sustained demand must park a
            # low-priority co-tenant well inside the pre-kill window.
            "VTPU_PREEMPT_AFTER_MS": "150",
            "VTPU_PREEMPT_MAX_PARK_S": "1",
        })
        if self.sched.broker_faults:
            env["VTPU_FAULTS"] = self.sched.broker_faults
            env["VTPU_FAULTS_SEED"] = str(self.sched.seed)
        else:
            env.pop("VTPU_FAULTS", None)
        return env

    def spawn_broker(self) -> None:
        cmd = [sys.executable, "-m", "vtpu.runtime.server",
               "--socket", self.sock, "--hbm-limit", "64Mi",
               "--core-limit", "50", "--journal-dir", self.jdir]
        self.broker = subprocess.Popen(
            cmd, cwd=REPO, env=self._broker_env(),
            stdout=self.broker_log, stderr=self.broker_log)

    def spawn_tenants(self) -> List[Tuple[subprocess.Popen, str]]:
        procs = []
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
            "VTPU_LOG_LEVEL": "0",
            # The reconnect budget must cover a broker respawn (jax
            # import + journal recovery), with margin.
            "VTPU_RECONNECT_TIMEOUT_S": "30",
        })
        if self.sched.tenant_faults:
            env["VTPU_FAULTS"] = self.sched.tenant_faults
            env["VTPU_FAULTS_SEED"] = str(self.sched.seed)
        else:
            env.pop("VTPU_FAULTS", None)
        for i in range(self.sched.tenants):
            progress = os.path.join(self.tmp, f"t{i}.progress")
            cmd = [sys.executable, "-m", "vtpu.tools.chaos",
                   "--tenant-child", "--socket", self.sock,
                   "--name", f"churn-{self.sched.seed}-{i}",
                   "--progress", progress,
                   "--duration", str(self.sched.duration),
                   "--child-seed", str(self.sched.seed * 100 + i),
                   "--child-priority",
                   str(self.sched.priorities[i]),
                   "--hbm", str(8 << 20), "--core", "50"]
            tenv = env
            if i == self.fastlane_idx:
                # vtpu-fastlane under kill -9 (docs/PERF.md): tenant 1
                # rides the interposer-only data plane; the crash must
                # degrade exactly like degraded mode — fail closed,
                # zero region leak, epoch resume builds a fresh lane
                # and the ring makes progress again.  With 2+ tenants
                # the lane is SHARDED over chips 0,1 (per-chip rings +
                # completion-vector join), so the kill -9 lands
                # mid-sharded-flight (vtpu-fastlane-everywhere).
                tenv = dict(env)
                tenv["VTPU_FASTLANE"] = "1"
                cmd.append("--child-fastlane")
                cmd.extend(["--child-devices", "0,1"])
            procs.append((subprocess.Popen(
                cmd, cwd=REPO, env=tenv, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL, text=True), progress))
        return procs

    # -- live polling ------------------------------------------------------

    def _poll_once(self) -> None:
        resp = _admin_stats(self.sock)
        if not resp or not resp.get("ok"):
            return
        now = time.time()
        for name, st in (resp.get("tenants") or {}).items():
            lease = int(st.get("lease_us", 0))
            if lease < 0:
                self.violations.append(
                    f"[lease-nonnegative] tenant {name} lease_us="
                    f"{lease} at t={now:.2f}")
            if lease > LEASE_CLAMP_US:
                self.violations.append(
                    f"[token-conservation] tenant {name} lease_us="
                    f"{lease} exceeds the one-quantum clamp "
                    f"({LEASE_CLAMP_US})")
            # Burst-credit bounds hold LIVE across the churn — and
            # across the kill -9 resume (a replayed balance must
            # never exceed the cap or go negative).
            credit = int(st.get("credit_us", 0))
            if credit < 0 or credit > CREDIT_CAP_US:
                self.violations.append(
                    f"[credit-bounds] tenant {name} credit_us="
                    f"{credit} outside [0, {CREDIT_CAP_US}] at "
                    f"t={now:.2f}")
            if st.get("preempted"):
                self.parked_seen.append(now)
            self.max_preemptions = max(
                self.max_preemptions, int(st.get("preemptions", 0)))
            if name.endswith(f"-{self.fastlane_idx}") \
                    and st.get("fastlane"):
                self.fastlane_polls.append(
                    (now, int(st["fastlane"].get("ring_steps", 0))))
        self.polls.append({"t": now, "resp": resp})
        slo = _admin_slo(self.sock)
        if slo and slo.get("ok") and slo.get("enabled"):
            rows = {}
            for name, row in (slo.get("tenants") or {}).items():
                wins = row.get("windows") or {}
                short = wins[min(wins, key=float)] if wins else {}
                rows[name] = {
                    "count": int((row.get("phases") or {})
                                 .get("e2e", {}).get("count", 0)),
                    "restored": int(row.get("restored_count", 0)),
                    "attainment_pct": short.get("attainment_pct"),
                    "burn_rate": short.get("burn_rate"),
                }
            self.slo_polls.append({"t": now, "rows": rows})

    # -- the schedule ------------------------------------------------------

    def run(self) -> Dict[str, Any]:
        sched = self.sched
        result: Dict[str, Any] = {
            "seed": sched.seed, "tenants": sched.tenants,
            "kill_at_s": round(sched.kill_at, 2),
            "broker_faults": sched.broker_faults,
            "tenant_faults": sched.tenant_faults,
        }
        self.spawn_broker()
        if not _wait_socket(self.sock, 30.0):
            raise RuntimeError("broker never bound its socket")
        tenants = self.spawn_tenants()
        t0 = time.time()
        t_kill = t0 + sched.kill_at
        # Preferred kill instant: the FIRST poll after this point that
        # observes a tenant preemption-PARKED pulls the kill forward —
        # the crash then provably lands mid-suspend, and the successor
        # must recover the parked state from the journal.
        t_kill_early = t0 + sched.kill_at * 0.6
        killed = False
        respawned_at = None
        # Drive the schedule: poll STATS, kill on time, respawn.
        while any(p.poll() is None for p, _ in tenants):
            now = time.time()
            if not killed and now >= t_kill_early and now < t_kill \
                    and self.parked_seen \
                    and now - self.parked_seen[-1] < 0.4:
                self.log(f"[chaos s{sched.seed}] tenant parked — "
                         f"pulling the kill forward to mid-suspend")
                t_kill = now
            if not killed and now >= t_kill:
                # THE kill -9: mid-EXEC_BATCH, leases live, PUTs in
                # flight.  SIGKILL — no handler runs, no snapshot is
                # taken; recovery is the journal's problem.
                self.broker.send_signal(signal.SIGKILL)
                self.broker.wait(timeout=10)
                killed = True
                t_kill = now
                self.log(f"[chaos s{sched.seed}] broker SIGKILLed at "
                         f"+{now - t0:.2f}s")
                self.spawn_broker()
                if not _wait_socket(self.sock, 30.0):
                    raise RuntimeError(
                        "respawned broker never bound its socket")
                respawned_at = time.time()
            if killed or now < t_kill - 0.3:
                # No STATS poll in the final pre-kill window: a probe
                # quiesce there would drain the very in-flight state
                # the kill is supposed to cut through.
                self._poll_once()
            time.sleep(0.25)
        reports = []
        for p, _prog in tenants:
            out, _ = p.communicate(timeout=30)
            rep = None
            for line in (out or "").splitlines():
                if line.startswith("TENANT_RESULT "):
                    rep = json.loads(line[len("TENANT_RESULT "):])
            if p.returncode != 0 or rep is None:
                self.violations.append(
                    f"[epoch-resume] tenant child rc={p.returncode} "
                    f"without a result (crashed under churn)")
                continue
            reports.append(rep)
        result["tenant_reports"] = reports
        self._judge(result, tenants, t_kill, respawned_at)
        self._teardown()
        result["violations"] = self.violations
        result["ok"] = not self.violations
        return result

    # -- verdicts ----------------------------------------------------------

    @staticmethod
    def _rate(samples: List[Tuple[float, int]], lo: float,
              hi: float) -> float:
        """Aggregate steps/s inside [lo, hi] from (ts, steps) rows."""
        inside = [(t, s) for t, s in samples if lo <= t <= hi]
        if len(inside) < 2:
            return 0.0
        (ta, sa), (tb, sb) = inside[0], inside[-1]
        return (sb - sa) / max(tb - ta, 1e-6)

    def _judge(self, result: Dict[str, Any], tenants, t_kill: float,
               respawned_at: Optional[float]) -> None:
        sched = self.sched
        # Per-tenant progress curves.
        curves: List[List[Tuple[float, int]]] = []
        for _p, prog in tenants:
            rows: List[Tuple[float, int]] = []
            try:
                with open(prog) as f:
                    for line in f:
                        parts = line.split()
                        if len(parts) == 2:
                            rows.append((float(parts[0]),
                                         int(parts[1])))
            except OSError:
                pass
            curves.append(rows)
        # Recovery: first progress past the kill, per tenant; the
        # SLOWEST tenant defines the system's recovery.
        rec_ts = []
        for rows in curves:
            at_kill = max((s for t, s in rows if t <= t_kill),
                          default=0)
            after = [t for t, s in rows if t > t_kill and s > at_kill]
            if after:
                rec_ts.append(after[0])
        if len(rec_ts) == len(curves) and rec_ts:
            result["recovery_ms"] = round(
                (max(rec_ts) - t_kill) * 1e3, 1)
        else:
            self.violations.append(
                "[epoch-resume] some tenant never made progress after "
                "the kill")
            result["recovery_ms"] = None
        # vtpu-fastlane churn verdicts: the lane must have been HOT
        # (ring-admitted steps observed) before the kill, and the
        # respawned broker must serve a FRESH lane that progresses —
        # killing the broker under fastlane load degrades exactly like
        # degraded mode and the epoch resume drains/rebuilds the ring.
        if self.fastlane_idx >= 0:
            pre = [n for t, n in self.fastlane_polls if t <= t_kill]
            post = [n for t, n in self.fastlane_polls
                    if respawned_at is not None and t > respawned_at]
            result["fastlane_pre_kill_ring_steps"] = max(pre, default=0)
            result["fastlane_post_kill_ring_steps"] = max(post,
                                                          default=0)
            # The lane must have engaged at SOME point of the run (a
            # loaded quick-mode host can pull the kill forward before
            # the tenant's first route primes — the post-respawn lane
            # then carries the proof); a run whose fastlane tenant
            # NEVER admitted a ring step proves nothing.
            if max(pre, default=0) <= 0 and max(post, default=0) <= 0 \
                    and self.fastlane_polls:
                self.violations.append(
                    "[fastlane-churn] the fastlane tenant never "
                    "admitted a ring step (pre or post kill)")
            if post and max(post) <= 0 and max(pre, default=0) > 0:
                self.violations.append(
                    "[fastlane-churn] the respawned broker's fresh "
                    "lane never admitted a ring step")
        # Throughput: aggregate across tenants, steady windows.
        pre_lo, pre_hi = t_kill - 2.0, t_kill - 0.1
        rec_edge = (max(rec_ts) if rec_ts else
                    (respawned_at or t_kill)) + 1.0
        end = min((rows[-1][0] for rows in curves if rows),
                  default=rec_edge)
        pre = sum(self._rate(rows, pre_lo, pre_hi) for rows in curves)
        post = sum(self._rate(rows, rec_edge, end - 0.1)
                   for rows in curves)
        result["pre_crash_steps_per_s"] = round(pre, 1)
        result["post_crash_steps_per_s"] = round(post, 1)
        ratio = post / pre if pre > 0 else 0.0
        result["recovery_ratio"] = round(ratio, 3)
        # With mixed priorities the preemption policy PARKS the lower
        # tier in duty cycles (max-park/cooldown), so short aggregate
        # windows straddle different park phases on the two sides of
        # the kill: the never-parked priority-0 tenant keeps the
        # strict floor, the park-modulated aggregate a looser one.
        mixed = len(set(self.sched.priorities)) > 1
        # Load-aware floor (docs/CHAOS.md): the no-fault control
        # cell's stability factor relaxes the threshold exactly as
        # much as the UNPERTURBED system wobbles on this machine.
        agg_floor = (0.75 if mixed else RECOVERY_RATIO) \
            * self.floor_scale
        result["throughput_floor"] = round(agg_floor, 3)
        hi_ratio = None
        if mixed and pre > 0:
            hi_idx = self.sched.priorities.index(0)
            hi_pre = self._rate(curves[hi_idx], pre_lo, pre_hi)
            hi_post = self._rate(curves[hi_idx], rec_edge, end - 0.1)
            hi_ratio = (hi_post / hi_pre) if hi_pre > 0 else None
            result["hi_recovery_ratio"] = (round(hi_ratio, 3)
                                          if hi_ratio is not None
                                          else None)
        if pre <= 0:
            self.violations.append(
                "[throughput-recovery] no pre-crash steady state "
                "measured")
        elif ratio < agg_floor:
            if mixed and hi_ratio is not None \
                    and hi_ratio >= RECOVERY_RATIO:
                # Mixed priorities park-cycle the lower tier in duty
                # cycles, so the short aggregate windows straddle
                # different park phases on the two sides of the kill
                # — load noise, not a recovery regression.  The
                # PROTECTED priority-0 tenant recovering at the
                # strict floor (plus the hard per-tenant progress /
                # resume / durability checks above) is the recovery
                # evidence; the aggregate dip is recorded, not red.
                result["throughput_waived_by_hi_recovery"] = True
                self.log(f"[chaos s{self.sched.seed}] aggregate "
                         f"post-crash ratio {ratio:.2f} below floor "
                         f"{agg_floor:.2f} but the priority-0 tenant "
                         f"recovered {hi_ratio:.2f}x — park-phase "
                         f"noise, recorded not asserted")
            else:
                self.violations.append(
                    f"[throughput-recovery] post-crash throughput "
                    f"{post:.0f} steps/s is {ratio:.2f}x pre-crash "
                    f"({pre:.0f}) — floor is {agg_floor:.2f} "
                    f"(load factor {self.floor_scale:.2f})")
        # Per-tenant verdicts from the children.
        for rep in result.get("tenant_reports", []):
            if rep.get("state_lost"):
                self.violations.append(
                    f"[epoch-resume] tenant {rep['tenant']} lost state "
                    f"{rep['state_lost']}x (journal resume failed)")
            if not rep.get("resumes"):
                self.violations.append(
                    f"[epoch-resume] tenant {rep['tenant']} never saw "
                    f"a resumed reconnect")
            if not rep.get("durability_ok", True):
                self.violations.append(
                    f"[reply-durability] tenant {rep['tenant']}'s "
                    f"acked probe PUT did not survive the crash "
                    f"bit-identical")
        # Ledger balance: wait for the broker to tear every tenant
        # down, then the region must read ZERO bytes on every slot.
        deadline = time.monotonic() + 20.0
        remaining = None
        while time.monotonic() < deadline:
            resp = _admin_stats(self.sock)
            if resp and resp.get("ok") and not resp.get("tenants") \
                    and not (resp.get("journal") or {}).get(
                        "tenants_awaiting_resume"):
                remaining = resp
                break
            time.sleep(0.2)
        leak = self._region_leak_bytes()
        result["region_leak_bytes"] = leak
        if remaining is None:
            self.violations.append(
                "[hbm-ledger-balance] broker never finished tenant "
                "teardown (cannot audit the ledger)")
        elif leak != 0:
            self.violations.append(
                f"[hbm-ledger-balance] region ledgers hold {leak} "
                f"bytes after every tenant closed (quota leak != 0)")
        # vtpu-elastic preemption verdicts (docs/SCHEDULING.md): with a
        # priority-0 tenant saturating against priority-1 co-tenants,
        # the preemption policy must ENGAGE during the run — a park
        # observed live, or a preemption counter that moved.  The
        # parked tenant's own recovery/progress/durability are already
        # judged by the per-tenant checks above, and the zero-leak
        # ledger audit proves credits and floor state wound down
        # consistent after the mid-suspend crash.
        result["preemptions_max"] = self.max_preemptions
        pk = [t for t in self.parked_seen if t <= t_kill + 0.1]
        result["killed_while_parked"] = bool(pk
                                             and t_kill - pk[-1] < 0.5)
        if 0 in sched.priorities and len(set(sched.priorities)) > 1:
            if not self.parked_seen and self.max_preemptions == 0:
                self.violations.append(
                    "[preemption] priority-0 tenant saturated against "
                    "priority-1 co-tenants for the whole schedule but "
                    "no preemption ever engaged")
        if remaining is not None:
            jstats = remaining.get("journal") or {}
            result["tenants_readopted"] = jstats.get(
                "tenants_readopted")
            if int(jstats.get("tenants_readopted", 0) or 0) \
                    < sched.tenants:
                self.violations.append(
                    f"[epoch-resume] broker re-adopted only "
                    f"{jstats.get('tenants_readopted')} of "
                    f"{sched.tenants} tenants")
        self._judge_slo(result, curves, t_kill, respawned_at)

    def _judge_slo(self, result: Dict[str, Any], curves,
                   t_kill: float,
                   respawned_at: Optional[float]) -> None:
        """SLO-plane churn verdicts (docs/OBSERVABILITY.md): the
        attainment timeline spans the kill, and the sketches SURVIVE
        the epoch resume without double-counting in-flight requests.

        The judge reads the broker's own restore evidence — the
        ``restored_count`` each resumed row reports (the e2e count as
        replayed from the journal).  Client step curves can NOT stand
        in for sketch counts: replies go out at dispatch while the
        sketch counts at metering retire, so a fast tenant's client
        counter runs seconds of device-queue depth AHEAD of the plane
        (the dispatch-ahead lag).  Per resumed tenant, with C_pre the
        last pre-kill poll's sketch count and S_gap the client steps
        between that poll and the kill:

          restored >= C_pre/2            history survived (the journal
                                         cadence lags at most a tick)
          restored <= C_pre + S_gap + s  no double count — a replay
                                         that re-ingested live history
                                         would land near 2*C_pre
        """
        pre = [p for p in self.slo_polls if p["t"] < t_kill]
        post_edge = respawned_at or t_kill
        post = [p for p in self.slo_polls if p["t"] > post_edge]
        result["slo_timeline"] = {
            "samples": len(self.slo_polls),
            "pre": pre[-1]["rows"] if pre else None,
            "post": post[-1]["rows"] if post else None,
        }
        if not pre or not post:
            self.violations.append(
                f"[slo-timeline] no SLO samples on both sides of the "
                f"kill (pre={len(pre)} post={len(post)}) — the "
                f"always-on plane must answer across the churn")
            return
        c_pre = pre[-1]["rows"]
        t_pre = pre[-1]["t"]
        for i, rows in enumerate(curves):
            # Tenant names follow the spawn order: churn-<seed>-<i>.
            name = f"churn-{self.sched.seed}-{i}"
            pre_n = int((c_pre.get(name) or {}).get("count", 0))
            if pre_n == 0:
                continue  # tenant bound after the last pre-kill poll
            # The restore evidence from the respawned broker: the MAX
            # over post polls (late polls may land after the tenant's
            # clean teardown dropped its row — a reused name must
            # start at zero, restored_count included).
            restored = max(
                (int((p["rows"].get(name) or {}).get("restored", 0))
                 for p in post), default=0)
            # Client steps between the last pre-kill poll and the
            # kill: traffic the journaled sketch may legitimately
            # carry past the poll's count.
            s_at_poll = max((s for t, s in rows if t <= t_pre),
                            default=0)
            s_at_kill = max((s for t, s in rows if t <= t_kill),
                            default=s_at_poll)
            s_gap = max(s_at_kill - s_at_poll, 0)
            # Survival floor at 5% of the last poll: the journal
            # cadence (VTPU_SLO_JOURNAL_S) stretches under a
            # GIL-saturated broker, so the journaled sketch can trail
            # the live poll by several seconds of traffic — the check
            # proves the history ARRIVED through the restore arm, the
            # no-double-count bound below proves it is not inflated.
            if restored < max(pre_n // 20, 1):
                self.violations.append(
                    f"[slo-survival] tenant {name} resumed with a "
                    f"restored e2e count of {restored} against a "
                    f"pre-crash count of {pre_n} — attainment history "
                    f"did not survive the kill -9")
            slack = 512 + pre_n // 4
            if restored > pre_n + s_gap + slack:
                self.violations.append(
                    f"[slo-double-count] tenant {name} resumed with "
                    f"restored count {restored} exceeding pre-crash "
                    f"{pre_n} + kill-window steps {s_gap} + slack "
                    f"{slack} — resume double-counted in-flight "
                    f"requests")

    def _region_leak_bytes(self) -> int:
        import glob as globmod

        from ...shim.core import SharedRegion
        total = 0
        for path in [self.sock + ".shr"] + sorted(
                globmod.glob(self.sock + ".shr.chip*")):
            if not os.path.exists(path):
                continue
            r = SharedRegion(path)
            try:
                for d in range(r.ndevices):
                    total += int(r.device_stats(d).used_bytes)
            finally:
                r.close()
        return total

    def _teardown(self) -> None:
        if self.broker is not None and self.broker.poll() is None:
            self.broker.terminate()
            try:
                self.broker.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.broker.kill()
        self.broker_log.close()


class ControlRun(ChurnRun):
    """The no-fault CONTROL cell: the same broker + tenant shape as a
    churn schedule, shorter, never killed and never fault-injected.
    Its early-vs-late steady-state throughput ratio measures how much
    the UNPERTURBED system wobbles on this machine right now — the
    load factor the real schedule's recovery verdicts scale by."""

    def run_control(self) -> Dict[str, Any]:
        self.spawn_broker()
        if not _wait_socket(self.sock, 30.0):
            raise RuntimeError("control broker never bound its socket")
        tenants = self.spawn_tenants()
        while any(p.poll() is None for p, _ in tenants):
            time.sleep(0.25)
        for p, _prog in tenants:
            try:
                p.communicate(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()
        curves: List[List[Tuple[float, int]]] = []
        for _p, prog in tenants:
            rows: List[Tuple[float, int]] = []
            try:
                with open(prog) as f:
                    for line in f:
                        parts = line.split()
                        if len(parts) == 2:
                            rows.append((float(parts[0]),
                                         int(parts[1])))
            except OSError:
                pass
            curves.append(rows)
        self._teardown()
        t_lo = min((rows[0][0] for rows in curves if rows),
                   default=0.0)
        t_hi = max((rows[-1][0] for rows in curves if rows),
                   default=0.0)
        # Skip the compile/jax-import ramp; split the steady window.
        lo = t_lo + min(3.0, max((t_hi - t_lo) * 0.3, 1.0))
        mid = (lo + t_hi) / 2.0
        early = sum(self._rate(rows, lo, mid) for rows in curves)
        late = sum(self._rate(rows, mid, t_hi) for rows in curves)
        if early <= 0 or late <= 0:
            factor = 1.0  # no signal: keep the strict floor
        else:
            factor = min(late, early) / max(late, early)
        return {"early_steps_per_s": round(early, 1),
                "late_steps_per_s": round(late, 1),
                "factor": round(max(min(factor, 1.0), 0.25), 3)}


def measure_control(seed: int, tenants: int = 4,
                    quick: bool = False, log=print) -> Dict[str, Any]:
    """Run one no-fault control cell for a seed; returns its stats
    (incl. the ``factor`` the churn thresholds scale by)."""
    sched = Schedule(seed, tenants, quick)
    sched.duration = 6.0 if quick else 8.0
    sched.broker_faults = ""
    sched.tenant_faults = ""
    sched.kill_at = sched.duration * 10  # never fires
    try:
        return ControlRun(sched, log=log).run_control()
    except (OSError, RuntimeError) as e:
        log(f"[chaos s{seed}] control cell failed ({e}); keeping the "
            f"strict thresholds")
        return {"factor": 1.0, "error": str(e)}


def run_schedule(seed: int, tenants: int = 4, quick: bool = False,
                 log=print, control: bool = True,
                 floor_scale: Optional[float] = None) -> Dict[str, Any]:
    """``floor_scale``: a load factor ALREADY measured by the caller
    (e.g. the failover suite's control cell) — applied to the strict
    per-seed floors without re-running the control cell here.  Ignored
    when ``control`` is on (the fresh measurement wins)."""
    factor = 1.0 if floor_scale is None else float(floor_scale)
    ctl: Optional[Dict[str, Any]] = None
    if control:
        ctl = measure_control(seed, tenants=tenants, quick=quick,
                              log=log)
        factor = float(ctl.get("factor", 1.0))
    sched = Schedule(seed, tenants, quick)
    out = ChurnRun(sched, log=log, floor_scale=factor).run()
    if ctl is not None:
        out["control"] = ctl
    return out
