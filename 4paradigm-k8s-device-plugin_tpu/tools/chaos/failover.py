"""Failover chaos cell: kill -9 the PRIMARY with a live hot standby.

One failover schedule (= one seed) extends the churn suite's shape
(driver.py) with a standby broker process (``python -m
vtpu.runtime.replication``) following the primary's journal stream:

  1. spawn a journal-enabled PRIMARY, a STANDBY following it over the
     admin socket, and 4+ real tenant children under pipelined
     EXEC_BATCH load (tenant 1 on the fastlane data plane);
  2. SIGKILL the primary mid-flight and do NOT respawn it — the
     standby confirms the death, fences the old epoch, claims the
     listen socket and serves HELLO ``resume_epoch`` from its
     already-applied state;
  3. measure per-tenant BLACKOUT (first post-kill progress minus the
     kill instant) and hold the live system to the churn rows ACROSS
     the takeover: every tenant resumes on the standby, region ledger
     zero bytes after teardown, credits within cap, leases clamped,
     and the fastlane tenant's fresh lane progresses.

The verdict is relative AND absolute: blackout p99 must beat the
load-scaled 1s budget (docs/FAILOVER.md blackout table), and the
driver's respawn baseline — measured in the SAME run by the normal
churn schedule — is recorded next to it so the win over the respawn
path is visible per run, not assumed.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

from .driver import (ChurnRun, Schedule, _admin_stats, _wait_socket,
                     CREDIT_CAP_US, LEASE_CLAMP_US, REPO)

# Absolute blackout budget (ms): the acceptance bound the standby
# takeover must beat.  Scaled by the control cell's load factor so a
# saturated CI runner reads as load, not as a regression.
BLACKOUT_BUDGET_MS = 1000.0


class FailoverRun(ChurnRun):
    """One failover schedule: primary + standby + tenants, the kill
    lands on the primary and the STANDBY serves the rest of the run."""

    def __init__(self, sched: Schedule, workdir: Optional[str] = None,
                 log=print, load_factor: float = 1.0):
        # Uniform priorities: the churn suite already proves the
        # kill-mid-park path (and the standby re-parks a preempted
        # tenant correctly — tests/test_failover.py failover-mid-park).
        # THIS cell measures blackout, and a preemption-parked
        # tenant's held queue would read as seconds of "blackout"
        # that are really the park doing its job.
        sched.priorities = [1] * sched.tenants
        super().__init__(sched, workdir=workdir, log=log)
        self.sdir = os.path.join(self.tmp, "journal-standby")
        self.standby: Optional[subprocess.Popen] = None
        self.standby_log = open(os.path.join(self.tmp, "standby.log"),
                                "ab")
        self.load_factor = max(min(load_factor, 1.0), 0.25)

    def spawn_standby(self) -> None:
        env = self._broker_env()
        env.pop("VTPU_FAULTS", None)
        env["VTPU_JOURNAL_DIR"] = self.sdir
        cmd = [sys.executable, "-m", "vtpu.runtime.replication",
               "--socket", self.sock, "--journal-dir", self.sdir,
               "--hbm-limit", "64Mi", "--core-limit", "50",
               "--confirm-s", "0.3"]
        self.standby = subprocess.Popen(
            cmd, cwd=REPO, env=env,
            stdout=self.standby_log, stderr=self.standby_log)

    def _wait_standby_attached(self, timeout: float = 20.0) -> bool:
        """Wait until the primary's STATS shows a follower — the kill
        must land on a PRIMARY that actually has a live standby."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            resp = _admin_stats(self.sock)
            repl = (resp or {}).get("replication") or {}
            if any(not f.get("dropped")
                   for f in repl.get("followers") or []):
                return True
            time.sleep(0.1)
        return False

    def run(self) -> Dict[str, Any]:
        sched = self.sched
        result: Dict[str, Any] = {
            "seed": sched.seed, "tenants": sched.tenants,
            "kill_at_s": round(sched.kill_at, 2),
            "cell": "failover",
            "load_factor": round(self.load_factor, 3),
        }
        self.spawn_broker()
        if not _wait_socket(self.sock, 30.0):
            raise RuntimeError("primary never bound its socket")
        self.spawn_standby()
        if not self._wait_standby_attached():
            self.violations.append(
                "[failover] standby never attached to the primary's "
                "replication stream")
        tenants = self.spawn_tenants()
        t0 = time.time()
        t_kill = t0 + sched.kill_at
        killed = False
        while any(p.poll() is None for p, _ in tenants):
            now = time.time()
            if not killed and now >= t_kill:
                # THE kill -9 — on the PRIMARY, with the standby live.
                # No respawn: the standby IS the successor.
                self.broker.send_signal(signal.SIGKILL)
                self.broker.wait(timeout=10)
                killed = True
                t_kill = now
                self.log(f"[failover s{sched.seed}] PRIMARY SIGKILLed "
                         f"at +{now - t0:.2f}s — standby takes over")
            if killed or now < t_kill - 0.3:
                self._poll_once()
            time.sleep(0.25)
        reports = []
        for p, _prog in tenants:
            out, _ = p.communicate(timeout=30)
            rep = None
            for line in (out or "").splitlines():
                if line.startswith("TENANT_RESULT "):
                    import json as jsonmod
                    rep = jsonmod.loads(line[len("TENANT_RESULT "):])
            if p.returncode != 0 or rep is None:
                self.violations.append(
                    f"[epoch-resume] tenant child rc={p.returncode} "
                    f"without a result (crashed under failover)")
                continue
            reports.append(rep)
        result["tenant_reports"] = reports
        self._judge_failover(result, tenants, t_kill)
        self._teardown()
        result["violations"] = self.violations
        result["ok"] = not self.violations
        return result

    # -- verdicts ----------------------------------------------------------

    def _judge_failover(self, result: Dict[str, Any], tenants,
                        t_kill: float) -> None:
        curves: List[List[Tuple[float, int]]] = []
        for _p, prog in tenants:
            rows: List[Tuple[float, int]] = []
            try:
                with open(prog) as f:
                    for line in f:
                        parts = line.split()
                        if len(parts) == 2:
                            rows.append((float(parts[0]),
                                         int(parts[1])))
            except OSError:
                pass
            curves.append(rows)
        # Per-tenant blackout: the gap between the kill and the FIRST
        # post-kill progress mark.  p99 over 4-8 tenants is the max.
        blackouts: List[float] = []
        for rows in curves:
            at_kill = max((s for t, s in rows if t <= t_kill),
                          default=0)
            after = [t for t, s in rows if t > t_kill and s > at_kill]
            if after:
                blackouts.append((after[0] - t_kill) * 1e3)
            else:
                self.violations.append(
                    "[epoch-resume] a tenant never made progress on "
                    "the standby after the primary kill")
        if blackouts:
            blackouts.sort()
            p99 = blackouts[min(int(len(blackouts) * 0.99),
                                len(blackouts) - 1)]
            result["blackout_ms"] = [round(b, 1) for b in blackouts]
            result["blackout_p99_ms"] = round(p99, 1)
            budget = BLACKOUT_BUDGET_MS / self.load_factor
            result["blackout_budget_ms"] = round(budget, 1)
            if p99 >= budget:
                self.violations.append(
                    f"[failover-blackout] blackout p99 {p99:.0f}ms "
                    f"exceeds the budget {budget:.0f}ms (1s scaled by "
                    f"load factor {self.load_factor:.2f})")
        # Every tenant resumed (state intact) on the standby.
        for rep in result.get("tenant_reports", []):
            if rep.get("state_lost"):
                self.violations.append(
                    f"[epoch-resume] tenant {rep['tenant']} lost "
                    f"state {rep['state_lost']}x across the takeover")
            if not rep.get("resumes"):
                self.violations.append(
                    f"[epoch-resume] tenant {rep['tenant']} never saw "
                    f"a resumed reconnect on the standby")
            if not rep.get("durability_ok", True):
                self.violations.append(
                    f"[reply-durability] tenant {rep['tenant']}'s "
                    f"acked probe PUT did not survive the takeover "
                    f"bit-identical")
        # Post-takeover serving identity: the socket answers, role
        # says took-over, the fence generation advanced.
        resp = _admin_stats(self.sock)
        repl = (resp or {}).get("replication") or {}
        result["takeover_role"] = repl.get("role")
        result["fence_generation"] = repl.get("fence_generation")
        if not resp or not resp.get("ok"):
            self.violations.append(
                "[failover] the standby never served STATS after the "
                "primary kill")
        elif repl.get("takeovers", 0) < 1:
            self.violations.append(
                "[failover] the serving broker reports zero takeovers "
                "— did the respawn path serve instead of the standby?")
        # Ledger audit across the takeover: wait for teardown, then
        # every region slot must read ZERO bytes (the standby's
        # region files — it claimed the same paths).
        deadline = time.monotonic() + 20.0
        settled = None
        while time.monotonic() < deadline:
            resp = _admin_stats(self.sock)
            if resp and resp.get("ok") and not resp.get("tenants") \
                    and not (resp.get("journal") or {}).get(
                        "tenants_awaiting_resume"):
                settled = resp
                break
            time.sleep(0.2)
        leak = self._region_leak_bytes()
        result["region_leak_bytes"] = leak
        if settled is None:
            self.violations.append(
                "[hbm-ledger-balance] the standby never finished "
                "tenant teardown (cannot audit the ledger)")
        elif leak != 0:
            self.violations.append(
                f"[hbm-ledger-balance] region ledgers hold {leak} "
                f"bytes after every tenant closed ACROSS the takeover")
        # Credits/leases stayed bounded across the takeover — the
        # polls already appended violations live (_poll_once); record
        # that the takeover was actually observed under load.
        post = [p for p in self.polls if p["t"] > t_kill]
        result["post_takeover_polls"] = len(post)
        # The live credit/lease bound checks (_poll_once) use the same
        # CREDIT_CAP_US / LEASE_CLAMP_US clamps across the takeover.
        result["credit_cap_us"] = CREDIT_CAP_US
        result["lease_clamp_us"] = LEASE_CLAMP_US
        if self.fastlane_idx >= 0:
            pre = [n for t, n in self.fastlane_polls if t <= t_kill]
            post_fl = [n for t, n in self.fastlane_polls
                       if t > t_kill]
            result["fastlane_pre_kill_ring_steps"] = max(pre,
                                                         default=0)
            result["fastlane_post_kill_ring_steps"] = max(post_fl,
                                                          default=0)
            if self.fastlane_polls and max(pre, default=0) <= 0 \
                    and max(post_fl, default=0) <= 0:
                self.violations.append(
                    "[fastlane-failover] the fastlane tenant never "
                    "admitted a ring step (pre or post takeover)")

    def _teardown(self) -> None:
        super()._teardown()
        if self.standby is not None and self.standby.poll() is None:
            self.standby.terminate()
            try:
                self.standby.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.standby.kill()
        self.standby_log.close()


def run_failover(seed: int, tenants: int = 4, quick: bool = False,
                 log=print, load_factor: float = 1.0,
                 baseline: bool = True) -> Dict[str, Any]:
    """One failover cell, plus (by default) the respawn-path baseline
    measured in the SAME run by the normal churn schedule — the
    blackout win is reported relative to it, never assumed."""
    sched = Schedule(seed, tenants, quick)
    out = FailoverRun(sched, log=log, load_factor=load_factor).run()
    if baseline:
        from .driver import run_schedule
        # The baseline churn run inherits THIS suite's measured load
        # factor instead of judging its respawn recovery against the
        # strict unscaled per-seed floors — on a loaded CI runner the
        # baseline would otherwise flake on timing the failover cell
        # itself was already excused from.
        base = run_schedule(seed, tenants=tenants, quick=quick,
                            log=log, control=False,
                            floor_scale=load_factor)
        out["respawn_baseline_ms"] = base.get("recovery_ms")
        out["respawn_baseline_ok"] = base.get("ok")
        p99 = out.get("blackout_p99_ms")
        if p99 is not None and base.get("recovery_ms"):
            out["blackout_vs_respawn"] = round(
                p99 / max(float(base["recovery_ms"]), 1e-3), 3)
    return out
