"""vtpu-chaos — deterministic fault schedules + the kill -9 churn suite.

PR 6's model checker (vtpu-mc) proves the quota/lease/crash-recovery
invariants under *simulated* schedules and journal cuts; this package
makes the same invariants hold under *real* injected faults on live
processes (docs/CHAOS.md):

  - ``python -m vtpu.tools.chaos`` runs seeded churn schedules: a real
    broker subprocess + 4+ real tenant processes driving pipelined
    EXEC_BATCH work with in-flight PUTs and live rate leases, the
    broker SIGKILLed mid-flight and respawned, every tenant resuming
    via HELLO epoch resume — then the live system is held to the PR 6
    invariant registry (HBM ledger balance to ZERO bytes of leak,
    lease non-negativity + quantum clamp, reply durability via a
    probe-array round trip, throughput recovery >= 90% of pre-crash).
  - schedules are DETERMINISTIC per seed (``--seeds 1,2,3,4,5`` in CI,
    plus one randomized seed printed for repro); fault variety comes
    from per-seed ``VTPU_FAULTS`` specs (runtime/faults.py).
  - ``vtpu-smi chaos --smoke`` is the dependency-light wiring check
    (fault grammar, seeded determinism, backoff jitter spread,
    degraded-gate plumbing — no jax, no subprocesses) the analyze CI
    job runs.
"""

from .cli import main  # noqa: F401

# The fixed CI schedule (one churn run per seed, deterministic).
DEFAULT_SEEDS = (1, 2, 3, 4, 5)
