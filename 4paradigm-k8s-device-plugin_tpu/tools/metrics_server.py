"""vtpu-metrics — HTTP metrics endpoint over vTPU accounting regions.

The reference exposes observability by lying to NVML so DCGM/nvidia-smi
see virtual devices (reference §2.9f).  libtpu's equivalent surface is
its localhost metrics service (which ``tpu-info`` reads) — but that
speaks about the RAW chip.  This server is the quota-adjusted stand-in:
it serves the shared-region view as

  GET /metrics   Prometheus text format (scrapeable; the reference has
                 no Prometheus endpoint at all — SURVEY §5)
  GET /json      machine-readable dump (regions -> devices -> procs)
  GET /healthz   liveness

Run in-container (region from the env contract) or on the node with
--scan over the monitor-mode shared dirs:

  python -m vtpu.tools.metrics_server --port 8431
  python -m vtpu.tools.metrics_server --scan /usr/local/vtpu/shared
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from ..shim.core import SharedRegion
from ..utils import envspec
from ..utils import logging as log
from .vtpu_smi import find_regions


class MetricsState:
    def __init__(self, scan: Optional[str], regions: List[str],
                 brokers: Optional[List[str]] = None,
                 metricsd: Optional[List[str]] = None,
                 cluster: Optional[str] = None):
        self.scan = scan
        self.explicit = regions
        self.brokers = brokers or []
        self.metricsd = metricsd or []
        self.cluster = cluster
        # Duty cycle: previous (busy_us, t) sample per (region, device).
        self._prev: Dict[tuple, tuple] = {}
        self.mu = threading.Lock()

    def paths(self) -> List[str]:
        return self.explicit or find_regions(self.scan)

    def collect_brokers(self) -> List[Dict]:
        """Per-tenant broker stats over the host-side admin socket
        (spill, residency, suspension — state the raw regions cannot
        show).  Best-effort and bounded: brokers are scraped
        concurrently with a short per-broker budget, and a dead,
        wedged, or garbling broker is skipped — it must never cost the
        scrape of healthy regions (Prometheus drops the WHOLE target
        past its scrape_timeout)."""
        from concurrent.futures import ThreadPoolExecutor

        from ..runtime import protocol as P
        from .vtpu_smi import _admin_request

        def scrape(sock):
            try:
                resp = _admin_request(sock, {"kind": P.STATS},
                                      timeout=2.0)
            except (OSError, P.ProtocolError) as e:
                log.warn("broker %s unreachable: %s", sock, e)
                return None
            if not resp.get("ok"):
                return None
            # The always-on SLO plane rides the same admin socket
            # (docs/OBSERVABILITY.md): per-tenant sketches, burn rates,
            # blame matrix, fairness.  Best-effort like everything
            # else on this path.
            slo = None
            try:
                s = _admin_request(sock, {"kind": P.SLO}, timeout=2.0)
                if s.get("ok"):
                    slo = s
            except (OSError, P.ProtocolError) as e:
                log.warn("broker %s SLO scrape failed: %s", sock, e)
            return {"broker": sock,
                    "tenants": resp.get("tenants", {}),
                    "suspended": resp.get("suspended", []),
                    "journal": resp.get("journal") or {},
                    "fastlane": resp.get("fastlane") or {},
                    "timers": resp.get("timers") or {},
                    "replication": resp.get("replication") or {},
                    "slo": slo}

        if not self.brokers:
            return []
        with ThreadPoolExecutor(max_workers=min(len(self.brokers),
                                                8)) as ex:
            return [r for r in ex.map(scrape, self.brokers)
                    if r is not None]

    def collect_cluster(self) -> Optional[Dict]:
        """Federation coordinator scrape (docs/FEDERATION.md): the
        CL_STATUS snapshot — node count, placement/migration counters,
        ledger size, conservation violations.  Best-effort like the
        broker scrape: a dead coordinator yields an explicit up=0
        gauge, never a failed scrape."""
        if not self.cluster:
            return None
        from ..runtime import cluster as cluster_mod
        try:
            return cluster_mod.status(self.cluster, timeout=2.0)
        except OSError as e:
            log.warn("cluster coordinator %s unreachable: %s",
                     self.cluster, e)
            return {"ok": False}

    def collect_metricsd(self) -> List[Dict]:
        """vtpu-metricsd self-gauges + virtualized device view over its
        own gRPC wire (docs/METRICSD.md) — node operators see what each
        tenant's stock tpu-info is being told, and whether pass-through
        denials are happening.  Best-effort: a dead metricsd is skipped,
        never fails the scrape."""
        from ..metricsd import server as metricsd_server

        out = []
        for addr in self.metricsd:
            try:
                import grpc

                from ..proto import tpu_metrics_grpc as mrpc
                from ..proto import tpu_metrics_pb2 as mpb
                ch = grpc.insecure_channel(addr)
                stub = mrpc.RuntimeMetricServiceStub(ch)
                item: Dict = {"metricsd": addr, "up": 1,
                              "self": {}, "devices": {}}
                for name in metricsd_server.SELF_METRICS:
                    resp = stub.GetRuntimeMetric(
                        mpb.MetricRequest(metric_name=name), timeout=2.0)
                    if resp.metric.metrics:
                        item["self"][name] = int(
                            resp.metric.metrics[0].gauge.as_int)
                for name in metricsd_server.VIRTUALIZED_METRICS:
                    resp = stub.GetRuntimeMetric(
                        mpb.MetricRequest(metric_name=name), timeout=2.0)
                    per_dev = {}
                    for m in resp.metric.metrics:
                        dev = int(m.attribute.value.int_attr)
                        val = (m.gauge.as_double
                               if m.gauge.WhichOneof("value") == "as_double"
                               else m.gauge.as_int)
                        per_dev[dev] = val
                    item["devices"][name] = per_dev
                ch.close()
                out.append(item)
            except Exception as e:  # noqa: BLE001 - scrape is best-effort
                log.warn("metricsd %s unreachable: %s", addr, e)
                out.append({"metricsd": addr, "up": 0,
                            "self": {}, "devices": {}})
        return out

    def collect(self) -> List[Dict]:
        out = []
        live_paths = set(self.paths())
        # Regions themselves vanish under pod churn (per-pod monitor-mode
        # caches): drop their samples or _prev grows without bound.
        with self.mu:
            for k in [k for k in self._prev if k[0] not in live_paths]:
                del self._prev[k]
        for path in live_paths:
            try:
                region = SharedRegion(path)
            except OSError:
                continue
            try:
                devices = []
                now = time.monotonic()
                for d in range(region.ndevices):
                    st = region.device_stats(d)
                    key = (path, d)
                    with self.mu:
                        prev = self._prev.get(key)
                        self._prev[key] = (st.busy_us, now)
                    duty = 0.0
                    if prev is not None and now > prev[1]:
                        duty = min(
                            (st.busy_us - prev[0])
                            / ((now - prev[1]) * 1e6) * 100.0, 100.0)
                    if st.limit_bytes == 0 and st.used_bytes == 0 \
                            and st.n_procs == 0:
                        continue
                    devices.append({
                        "device": d,
                        "hbm_used_bytes": int(st.used_bytes),
                        "hbm_limit_bytes": int(st.limit_bytes),
                        "hbm_peak_bytes": int(st.peak_bytes),
                        "core_limit_pct": int(st.core_limit_pct),
                        "duty_cycle_pct": round(max(duty, 0.0), 2),
                        "n_procs": int(st.n_procs),
                        "busy_us_total": int(st.busy_us),
                    })
                procs = []
                live_pkeys = set()
                for p in region.proc_stats():
                    pinfo = {
                        "pid": int(p.pid), "host_pid": int(p.host_pid),
                        "used_bytes": [int(b) for b in
                                       p.used_bytes[:region.ndevices]],
                        "busy_us": [int(b) for b in
                                    p.busy_us[:region.ndevices]],
                    }
                    # Per-tenant duty cycle (reference per-process
                    # utilization, nvmlDeviceGetProcessUtilization):
                    # delta of the proc's busy_us between scrapes.
                    # Keyed by HOST pid — in-namespace pids collide
                    # across containers (every pod's workload is pid 1).
                    duties = []
                    for d in range(region.ndevices):
                        pkey = (path, "proc", int(p.host_pid), d)
                        live_pkeys.add(pkey)
                        with self.mu:
                            pprev = self._prev.get(pkey)
                            self._prev[pkey] = (p.busy_us[d], now)
                        pd = 0.0
                        if pprev is not None and now > pprev[1]:
                            pd = min((p.busy_us[d] - pprev[0])
                                     / ((now - pprev[1]) * 1e6) * 100.0,
                                     100.0)
                        duties.append(round(max(pd, 0.0), 2))
                    pinfo["duty_cycle_pct"] = duties
                    procs.append(pinfo)
                # Prune samples of exited processes: per-pid keys are
                # unbounded under pod churn.
                with self.mu:
                    for k in [k for k in self._prev
                              if len(k) == 4 and k[0] == path
                              and k not in live_pkeys]:
                        del self._prev[k]
                out.append({"region": path, "devices": devices,
                            "procs": procs})
            finally:
                region.close()
        return out


def _esc(label: str) -> str:
    """Prometheus exposition label escaping.  Tenant names are
    TENANT-CONTROLLED (VTPU_TENANT / HELLO) — an unescaped quote or
    newline would corrupt the whole scrape body, taking down node
    observability from inside a container."""
    return (str(label).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def broker_prometheus(brokers: List[Dict]) -> str:
    lines = [
        "# HELP vtpu_tenant_hbm_used_bytes Accounted HBM per broker "
        "tenant (incl. overshoot residency).",
        "# TYPE vtpu_tenant_hbm_used_bytes gauge",
        "# HELP vtpu_tenant_hbm_limit_bytes HBM quota per broker tenant.",
        "# TYPE vtpu_tenant_hbm_limit_bytes gauge",
        "# HELP vtpu_tenant_host_spill_bytes Host-RAM spilled bytes per "
        "tenant (oversubscription).",
        "# TYPE vtpu_tenant_host_spill_bytes gauge",
        "# HELP vtpu_tenant_staged_resident_bytes Device-resident spill "
        "copies per tenant.",
        "# TYPE vtpu_tenant_staged_resident_bytes gauge",
        "# HELP vtpu_tenant_suspended 1 when the tenant is "
        "admin-suspended.",
        "# TYPE vtpu_tenant_suspended gauge",
        "# HELP vtpu_tenant_executions_total Steps executed per tenant.",
        "# TYPE vtpu_tenant_executions_total counter",
        # vtpu-slo (docs/OBSERVABILITY.md): the end-to-end latency
        # histogram is ALWAYS emitted for every known tenant, with
        # buckets DERIVED from the broker's own quantile sketch (a
        # stable ~2x log grid — not a hardcoded list) and trace-id
        # exemplars linking into the flight recorder when tracing is
        # on.
        "# HELP vtpu_tenant_latency_us End-to-end broker residency per "
        "execute (enqueue to device-ready), microseconds; buckets "
        "derived from the vtpu-slo sketch.",
        "# TYPE vtpu_tenant_latency_us histogram",
        "# HELP vtpu_tenant_slo_phase_us Phase latency quantiles "
        "(queue/bucket/device/e2e) from the always-on SLO sketches.",
        "# TYPE vtpu_tenant_slo_phase_us gauge",
        "# HELP vtpu_tenant_slo_attainment_ratio Fraction of requests "
        "inside the tenant's latency objective, per burn window.",
        "# TYPE vtpu_tenant_slo_attainment_ratio gauge",
        "# HELP vtpu_tenant_slo_burn_rate SLO burn rate (violation "
        "rate over error budget), per burn window.",
        "# TYPE vtpu_tenant_slo_burn_rate gauge",
        "# HELP vtpu_tenant_slo_burn_alert 1 when the short-window "
        "burn rate crossed the alert threshold.",
        "# TYPE vtpu_tenant_slo_burn_alert gauge",
        "# HELP vtpu_tenant_slo_target_us The tenant's end-to-end "
        "latency objective (explicit grant or quota-share default).",
        "# TYPE vtpu_tenant_slo_target_us gauge",
        "# HELP vtpu_tenant_blame_us_total Noisy-neighbor blame: "
        "cumulative queue+bucket wait of `tenant` attributed to "
        "`culprit` (rows sum to the tenant's measured wait).",
        "# TYPE vtpu_tenant_blame_us_total counter",
        "# HELP vtpu_tenant_fairness_ratio Attained device-time share "
        "over quota share (1.0 = exactly proportional).",
        "# TYPE vtpu_tenant_fairness_ratio gauge",
        "# HELP vtpu_broker_fairness_jain Jain fairness index over "
        "per-tenant attainment ratios (1.0 = perfectly fair).",
        "# TYPE vtpu_broker_fairness_jain gauge",
        # vtpu-trace flight-recorder rollups (docs/TRACING.md): where a
        # tenant's request time goes — queue vs token bucket vs device.
        # Only present when the broker runs with VTPU_TRACE=1.
        "# HELP vtpu_tenant_queue_wait_us_total Cumulative scheduler-"
        "queue wait per tenant (microseconds).",
        "# TYPE vtpu_tenant_queue_wait_us_total counter",
        "# HELP vtpu_tenant_bucket_wait_us_total Cumulative device-time "
        "token-bucket wait per tenant (microseconds).",
        "# TYPE vtpu_tenant_bucket_wait_us_total counter",
        "# HELP vtpu_tenant_device_us_total Cumulative device-phase "
        "wall time per tenant (microseconds).",
        "# TYPE vtpu_tenant_device_us_total counter",
        "# HELP vtpu_tenant_slow_op_captures Slow-op context captures "
        "currently held in the flight recorder.",
        "# TYPE vtpu_tenant_slow_op_captures gauge",
        # Journal health (docs/BROKER_RECOVERY.md): a growing journal
        # with an aging snapshot means compaction stalled; recoveries /
        # readopted / dropped tell operators whether broker restarts
        # are actually tenant-transparent.
        "# HELP vtpu_broker_journal_enabled 1 when the broker journals "
        "its state (crash-safe recovery).",
        "# TYPE vtpu_broker_journal_enabled gauge",
        "# HELP vtpu_broker_journal_size_bytes Journal log+snapshot "
        "bytes on disk.",
        "# TYPE vtpu_broker_journal_size_bytes gauge",
        "# HELP vtpu_broker_journal_last_snapshot_age_seconds Seconds "
        "since the last snapshot compaction (-1 = never).",
        "# TYPE vtpu_broker_journal_last_snapshot_age_seconds gauge",
        "# HELP vtpu_broker_recoveries_total Broker restarts that "
        "replayed a journal.",
        "# TYPE vtpu_broker_recoveries_total counter",
        "# HELP vtpu_broker_tenants_readopted_total Recovered tenants "
        "re-adopted by their reconnecting clients.",
        "# TYPE vtpu_broker_tenants_readopted_total counter",
        "# HELP vtpu_broker_tenants_recovery_dropped_total Recovered "
        "tenants dropped (dead pid, grace expiry, replaced).",
        "# TYPE vtpu_broker_tenants_recovery_dropped_total counter",
        "# HELP vtpu_broker_draining 1 while the broker refuses new "
        "tenants for a handover.",
        "# TYPE vtpu_broker_draining gauge",
        # vtpu-fastlane (docs/PERF.md): which data plane each tenant
        # is on, how deep its execute ring runs, and the shm-arena
        # footprint.
        "# HELP vtpu_fastlane_ring_depth Submitted-but-uncompleted "
        "descriptors in the tenant's fastlane execute ring.",
        "# TYPE vtpu_fastlane_ring_depth gauge",
        "# HELP vtpu_fastlane_ring_steps_total Executes admitted "
        "through the fastlane ring per tenant.",
        "# TYPE vtpu_fastlane_ring_steps_total counter",
        "# HELP vtpu_fastlane_fallback_steps_total Brokered-fallback "
        "executes while a fastlane lane existed, per tenant.",
        "# TYPE vtpu_fastlane_fallback_steps_total counter",
        "# HELP vtpu_fastlane_arena_bytes Total shm tensor-arena "
        "bytes (tx+rx) mapped for the tenant's lane.",
        "# TYPE vtpu_fastlane_arena_bytes gauge",
        "# HELP vtpu_fastlane_gate Lane gate word (0 open, 1 parked, "
        "2 closed).",
        "# TYPE vtpu_fastlane_gate gauge",
        "# HELP vtpu_broker_fastlane_lanes Active fastlane lanes on "
        "the broker.",
        "# TYPE vtpu_broker_fastlane_lanes gauge",
        # vtpu-fastlane-everywhere: sharded (multi-chip) lanes expose
        # each chip ordinal's ring separately — a lane hot on chip 1
        # but idle on chip 0 must be visible per chip, not averaged.
        "# HELP vtpu_fastlane_chip_ring_depth Per-chip-ordinal ring "
        "depth of a sharded fastlane lane.",
        "# TYPE vtpu_fastlane_chip_ring_depth gauge",
        "# HELP vtpu_fastlane_chip_ring_steps_total Per-chip-ordinal "
        "ring admissions of a sharded fastlane lane.",
        "# TYPE vtpu_fastlane_chip_ring_steps_total counter",
        "# HELP vtpu_fastlane_chip_gate Per-chip-ordinal lane gate "
        "word (0 open, 1 parked, 2 closed).",
        "# TYPE vtpu_fastlane_chip_gate gauge",
        # vtpu-timers (docs/PERF.md): the consolidated timer thread's
        # coalesced wakeups + the dispatcher/completer idle wakeups —
        # the idle broker's wakeup budget, CI-gated by broker-bench.
        "# HELP vtpu_broker_timer_wakeups_total Coalesced timer-wheel "
        "wakeups on the broker.",
        "# TYPE vtpu_broker_timer_wakeups_total counter",
        "# HELP vtpu_broker_dispatch_idle_wakeups_total Involuntary "
        "dispatcher idle wakeups summed over chips.",
        "# TYPE vtpu_broker_dispatch_idle_wakeups_total counter",
        "# HELP vtpu_broker_completer_wakeups_total Involuntary "
        "completion-loop idle wakeups summed over chips.",
        "# TYPE vtpu_broker_completer_wakeups_total counter",
        # vtpu-failover (docs/FAILOVER.md): a silently-stalled standby
        # must be visible BEFORE the primary dies — follower count,
        # worst lag, fence generation and takeover count per broker.
        "# HELP vtpu_repl_followers Subscribed replication followers "
        "(hot standbys) on this broker.",
        "# TYPE vtpu_repl_followers gauge",
        "# HELP vtpu_repl_lag_records Worst follower lag in journal "
        "records (0 with no followers).",
        "# TYPE vtpu_repl_lag_records gauge",
        "# HELP vtpu_repl_lag_bytes Worst follower stream-buffer "
        "backlog in bytes.",
        "# TYPE vtpu_repl_lag_bytes gauge",
        "# HELP vtpu_repl_seq Journal records appended by this "
        "instance (the replication stream sequence).",
        "# TYPE vtpu_repl_seq counter",
        "# HELP vtpu_repl_fence_generation Epoch-fence generation this "
        "broker claimed (a bump elsewhere fences it).",
        "# TYPE vtpu_repl_fence_generation gauge",
        "# HELP vtpu_repl_takeovers_total Standby takeovers this "
        "serving instance performed.",
        "# TYPE vtpu_repl_takeovers_total counter",
    ]
    for b in brokers:
        broker = _esc(os.path.basename(b["broker"]))
        j = b.get("journal") or {}
        if j:
            bl = f'{{broker="{broker}"}}'
            dropped = (j.get("tenants_dropped_dead", 0)
                       + j.get("tenants_dropped_expired", 0)
                       + j.get("tenants_dropped_replaced", 0))
            lines.append(f'vtpu_broker_journal_enabled{bl} '
                         f'{1 if j.get("enabled") else 0}')
            lines.append(f'vtpu_broker_journal_size_bytes{bl} '
                         f'{j.get("size_bytes", 0)}')
            lines.append(
                f'vtpu_broker_journal_last_snapshot_age_seconds{bl} '
                f'{j.get("last_snapshot_age_s", -1)}')
            lines.append(f'vtpu_broker_recoveries_total{bl} '
                         f'{j.get("recoveries_total", 0)}')
            lines.append(f'vtpu_broker_tenants_readopted_total{bl} '
                         f'{j.get("tenants_readopted", 0)}')
            lines.append(
                f'vtpu_broker_tenants_recovery_dropped_total{bl} '
                f'{dropped}')
            lines.append(f'vtpu_broker_draining{bl} '
                         f'{1 if j.get("draining") else 0}')
        repl = b.get("replication") or {}
        if repl:
            bl = f'{{broker="{broker}",role="{_esc(str(repl.get("role", "primary")))}"}}'
            followers = repl.get("followers") or []
            lines.append(f'vtpu_repl_followers{bl} {len(followers)}')
            lines.append(
                f'vtpu_repl_lag_records{bl} '
                f'{max((f.get("lag_records", 0) for f in followers), default=0)}')
            lines.append(
                f'vtpu_repl_lag_bytes{bl} '
                f'{max((f.get("lag_bytes", 0) for f in followers), default=0)}')
            lines.append(f'vtpu_repl_seq{bl} {repl.get("seq", 0)}')
            lines.append(f'vtpu_repl_fence_generation{bl} '
                         f'{repl.get("fence_generation", 0)}')
            lines.append(f'vtpu_repl_takeovers_total{bl} '
                         f'{repl.get("takeovers", 0)}')
        for name, t in sorted(b["tenants"].items()):
            labels = (f'{{broker="{broker}",tenant="{_esc(name)}",'
                      f'chip="{t["chip"]}"}}')
            lines.append(f'vtpu_tenant_hbm_used_bytes{labels} '
                         f'{t["used_bytes"]}')
            lines.append(f'vtpu_tenant_hbm_limit_bytes{labels} '
                         f'{t["limit_bytes"]}')
            lines.append(f'vtpu_tenant_host_spill_bytes{labels} '
                         f'{t["host_spill_bytes"]}')
            lines.append(f'vtpu_tenant_staged_resident_bytes{labels} '
                         f'{t["staged_resident_bytes"]}')
            lines.append(f'vtpu_tenant_suspended{labels} '
                         f'{1 if t.get("suspended") else 0}')
            lines.append(f'vtpu_tenant_executions_total{labels} '
                         f'{t["executions"]}')
            # vtpu-slo: ALWAYS emit the latency histogram per known
            # tenant — a tenant with no SLO row yet gets a zero-count
            # series, so dashboards never gap (the PR-2 histogram was
            # only present "when present" and its buckets were
            # hardcoded).
            slo_rows = ((b.get("slo") or {}).get("tenants") or {})
            _emit_tenant_slo(lines, labels, name,
                             slo_rows.get(name))
            fl = t.get("fastlane")
            if fl:
                lines.append(f'vtpu_fastlane_ring_depth{labels} '
                             f'{fl.get("ring_depth", 0)}')
                lines.append(f'vtpu_fastlane_ring_steps_total{labels} '
                             f'{fl.get("ring_steps", 0)}')
                lines.append(
                    f'vtpu_fastlane_fallback_steps_total{labels} '
                    f'{fl.get("fallback_steps", 0)}')
                lines.append(f'vtpu_fastlane_arena_bytes{labels} '
                             f'{fl.get("arena_bytes", 0)}')
                lines.append(f'vtpu_fastlane_gate{labels} '
                             f'{fl.get("gate", 0)}')
                for ordv, ch in enumerate(fl.get("chips") or ()):
                    clab = (f'{{broker="{broker}",'
                            f'tenant="{_esc(name)}",'
                            f'chip_ordinal="{ordv}"}}')
                    lines.append(
                        f'vtpu_fastlane_chip_ring_depth{clab} '
                        f'{ch.get("ring_depth", 0)}')
                    lines.append(
                        f'vtpu_fastlane_chip_ring_steps_total{clab} '
                        f'{ch.get("ring_steps", 0)}')
                    lines.append(
                        f'vtpu_fastlane_chip_gate{clab} '
                        f'{ch.get("gate", 0)}')
            tr = t.get("trace")
            if tr:
                lines.append(
                    f'vtpu_tenant_queue_wait_us_total{labels} '
                    f'{tr.get("queue_wait_us_total", 0)}')
                lines.append(
                    f'vtpu_tenant_bucket_wait_us_total{labels} '
                    f'{tr.get("bucket_wait_us_total", 0)}')
                lines.append(
                    f'vtpu_tenant_device_us_total{labels} '
                    f'{tr.get("device_us_total", 0)}')
                lines.append(
                    f'vtpu_tenant_slow_op_captures{labels} '
                    f'{tr.get("slow_captures", 0)}')
        fair = ((b.get("slo") or {}).get("fairness") or {})
        for name, row in sorted((fair.get("tenants") or {}).items()):
            lines.append(
                f'vtpu_tenant_fairness_ratio{{broker="{broker}",'
                f'tenant="{_esc(name)}"}} {row.get("ratio", 0.0)}')
        if fair:
            lines.append(f'vtpu_broker_fairness_jain'
                         f'{{broker="{broker}"}} '
                         f'{fair.get("jain", 1.0)}')
        flb = b.get("fastlane") or {}
        if flb:
            lines.append(f'vtpu_broker_fastlane_lanes'
                         f'{{broker="{broker}"}} '
                         f'{flb.get("lanes", 0)}')
        tm = b.get("timers") or {}
        if tm:
            bl = f'{{broker="{broker}"}}'
            lines.append(f'vtpu_broker_timer_wakeups_total{bl} '
                         f'{(tm.get("wheel") or {}).get("wakeups", 0)}')
            lines.append(
                f'vtpu_broker_dispatch_idle_wakeups_total{bl} '
                f'{tm.get("dispatch_idle_wakeups", 0)}')
            lines.append(
                f'vtpu_broker_completer_wakeups_total{bl} '
                f'{tm.get("completer_wakeups", 0)}')
    return "\n".join(lines) + "\n" if brokers else ""


def _emit_tenant_slo(lines: List[str], labels: str, name: str,
                     row: Optional[Dict]) -> None:
    """One tenant's SLO series (docs/OBSERVABILITY.md): the
    sketch-derived e2e histogram with trace-id exemplars, phase
    quantile gauges, per-window attainment/burn, the objective, and
    the noisy-neighbor blame counters."""
    base = labels[1:-1]  # strip braces; le/extra labels ride alongside
    buckets = (row or {}).get("e2e_buckets") or []
    count = ((row or {}).get("phases") or {}).get("e2e", {}) \
        .get("count", 0)
    sum_us = ((row or {}).get("phases") or {}).get("e2e", {}) \
        .get("sum_us", 0.0)
    # Exemplars: OpenMetrics syntax, attached to the first bucket that
    # covers the exemplar value — scrapers that predate exemplars
    # ignore everything after ' # '.
    exemplars = sorted(
        (v for v in ((row or {}).get("exemplars") or {}).values()
         if isinstance(v, (list, tuple)) and len(v) >= 3),
        key=lambda e: e[0])
    prev_le = 0.0
    for le, cum in buckets:
        line = (f'vtpu_tenant_latency_us_bucket{{{base},'
                f'le="{le}"}} {cum}')
        ex = next((e for e in exemplars
                   if prev_le < float(e[0]) <= float(le)), None)
        if ex is not None:
            line += (f' # {{trace_id="{_esc(ex[1])}"}} '
                     f'{ex[0]} {ex[2]}')
        lines.append(line)
        prev_le = float(le)
    lines.append(f'vtpu_tenant_latency_us_bucket{{{base},'
                 f'le="+Inf"}} {count}')
    lines.append(f'vtpu_tenant_latency_us_sum{{{base}}} {sum_us}')
    lines.append(f'vtpu_tenant_latency_us_count{{{base}}} {count}')
    if row is None:
        return
    for phase, ph in sorted((row.get("phases") or {}).items()):
        for q in ("p50_us", "p99_us"):
            lines.append(
                f'vtpu_tenant_slo_phase_us{{{base},phase="{phase}",'
                f'quantile="{q[:3]}"}} {ph.get(q, 0.0)}')
    for w, win in sorted((row.get("windows") or {}).items()):
        lines.append(
            f'vtpu_tenant_slo_attainment_ratio{{{base},'
            f'window_s="{w}"}} '
            f'{round(win.get("attainment_pct", 100.0) / 100.0, 4)}')
        lines.append(
            f'vtpu_tenant_slo_burn_rate{{{base},window_s="{w}"}} '
            f'{win.get("burn_rate", 0.0)}')
    obj = row.get("objective") or {}
    lines.append(f'vtpu_tenant_slo_target_us{{{base}}} '
                 f'{obj.get("target_us", 0.0)}')
    lines.append(f'vtpu_tenant_slo_burn_alert{{{base}}} '
                 f'{1 if row.get("burn_alert") else 0}')
    for culprit, us in sorted((row.get("blame") or {}).items()):
        lines.append(
            f'vtpu_tenant_blame_us_total{{{base},'
            f'culprit="{_esc(culprit)}"}} {us}')


def metricsd_prometheus(items: List[Dict]) -> str:
    """vtpu-metricsd gauges (docs/METRICSD.md): liveness, request and
    pass-through counters, and the quota-virtualized per-device values
    each tenant's stock tpu-info observes."""
    if not items:
        return ""
    lines = [
        "# HELP vtpu_metricsd_up 1 when the tenant metricsd answers its "
        "MetricService port.",
        "# TYPE vtpu_metricsd_up gauge",
        "# HELP vtpu_metricsd_requests_total MetricService RPCs served.",
        "# TYPE vtpu_metricsd_requests_total counter",
        "# HELP vtpu_metricsd_passthrough_total Non-sensitive metrics "
        "proxied from the real libtpu service.",
        "# TYPE vtpu_metricsd_passthrough_total counter",
        "# HELP vtpu_metricsd_passthrough_denied_total Quota-sensitive "
        "metric requests refused instead of proxied.",
        "# TYPE vtpu_metricsd_passthrough_denied_total counter",
        "# HELP vtpu_metricsd_virtual_value The quota-virtualized value "
        "served to the tenant, per metric name and device ordinal.",
        "# TYPE vtpu_metricsd_virtual_value gauge",
    ]
    suffix = {
        "vtpu.metricsd.requests.total": "requests_total",
        "vtpu.metricsd.passthrough.total": "passthrough_total",
        "vtpu.metricsd.passthrough.denied.total": "passthrough_denied_total",
    }
    for item in items:
        tgt = _esc(item["metricsd"])
        lines.append(f'vtpu_metricsd_up{{target="{tgt}"}} {item["up"]}')
        for name, val in sorted(item["self"].items()):
            lines.append(
                f'vtpu_metricsd_{suffix[name]}{{target="{tgt}"}} {val}')
        for name, per_dev in sorted(item["devices"].items()):
            for dev, val in sorted(per_dev.items()):
                lines.append(
                    f'vtpu_metricsd_virtual_value{{target="{tgt}",'
                    f'metric="{_esc(name)}",device="{dev}"}} {val}')
    return "\n".join(lines) + "\n"


def to_prometheus(infos: List[Dict]) -> str:
    lines = [
        "# HELP vtpu_hbm_used_bytes Accounted HBM usage per vTPU device.",
        "# TYPE vtpu_hbm_used_bytes gauge",
        "# HELP vtpu_hbm_limit_bytes HBM quota per vTPU device.",
        "# TYPE vtpu_hbm_limit_bytes gauge",
        "# HELP vtpu_duty_cycle_pct Device busy percentage since last "
        "scrape.",
        "# TYPE vtpu_duty_cycle_pct gauge",
        "# HELP vtpu_busy_us_total Cumulative device busy microseconds.",
        "# TYPE vtpu_busy_us_total counter",
        "# HELP vtpu_procs Live processes accounted on the device.",
        "# TYPE vtpu_procs gauge",
        "# HELP vtpu_proc_busy_us_total Cumulative device busy "
        "microseconds per process (tenant attribution).",
        "# TYPE vtpu_proc_busy_us_total counter",
    ]
    for info in infos:
        region = os.path.basename(os.path.dirname(info["region"])) or \
            os.path.basename(info["region"])
        for d in info["devices"]:
            labels = f'{{region="{region}",device="{d["device"]}"}}'
            lines.append(f'vtpu_hbm_used_bytes{labels} '
                         f'{d["hbm_used_bytes"]}')
            lines.append(f'vtpu_hbm_limit_bytes{labels} '
                         f'{d["hbm_limit_bytes"]}')
            lines.append(f'vtpu_duty_cycle_pct{labels} '
                         f'{d["duty_cycle_pct"]}')
            lines.append(f'vtpu_busy_us_total{labels} '
                         f'{d["busy_us_total"]}')
            lines.append(f'vtpu_procs{labels} {d["n_procs"]}')
        for p in info.get("procs", []):
            for d, busy in enumerate(p.get("busy_us", [])):
                if not busy:
                    continue
                # host pid: unique across containers (namespace pids
                # collide -> duplicate Prometheus series).
                labels = (f'{{region="{region}",device="{d}",'
                          f'pid="{p["host_pid"]}"}}')
                lines.append(f'vtpu_proc_busy_us_total{labels} {busy}')
    return "\n".join(lines) + "\n"


def cluster_prometheus(st: Optional[Dict]) -> str:
    """vtpu_cluster_* gauges from the federation coordinator's
    CL_STATUS (docs/FEDERATION.md).  Empty when no --cluster socket is
    configured; up=0 (and nothing else) when it is configured but
    unreachable — losing the coordinator must page as ITS outage, not
    corrupt the node gauges."""
    if st is None:
        return ""
    lines = [
        "# HELP vtpu_cluster_up 1 when the federation coordinator "
        "answered the scrape.",
        "# TYPE vtpu_cluster_up gauge",
        f"vtpu_cluster_up {1 if st.get('ok') else 0}",
    ]
    if not st.get("ok"):
        return "\n".join(lines) + "\n"
    nodes = st.get("nodes") or []
    alive = sum(1 for n in nodes if n.get("alive"))
    lines += [
        "# HELP vtpu_cluster_nodes Cluster members by liveness "
        "(heartbeat lease state).",
        "# TYPE vtpu_cluster_nodes gauge",
        f'vtpu_cluster_nodes{{state="alive"}} {alive}',
        f'vtpu_cluster_nodes{{state="down"}} {len(nodes) - alive}',
        "# HELP vtpu_cluster_placements_total Cross-node placements "
        "granted by this coordinator (journaled counter).",
        "# TYPE vtpu_cluster_placements_total counter",
        f"vtpu_cluster_placements_total "
        f"{int(st.get('placements_total', 0))}",
        "# HELP vtpu_cluster_migrations_total Cross-node migrations "
        "committed in the placement ledger.",
        "# TYPE vtpu_cluster_migrations_total counter",
        f"vtpu_cluster_migrations_total "
        f"{int(st.get('migrations_total', 0))}",
        "# HELP vtpu_cluster_ledger_bytes Size of the coordinator's "
        "placement-ledger journal log.",
        "# TYPE vtpu_cluster_ledger_bytes gauge",
        f"vtpu_cluster_ledger_bytes {int(st.get('ledger_bytes', 0))}",
        "# HELP vtpu_cluster_ledger_violations Conservation-check "
        "failures in the authoritative ledger (any non-zero value "
        "is a red alert).",
        "# TYPE vtpu_cluster_ledger_violations gauge",
        f"vtpu_cluster_ledger_violations "
        f"{len(st.get('violations') or [])}",
    ]
    return "\n".join(lines) + "\n"


def make_handler(state: MetricsState):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # noqa: D401 - quiet
            pass

        def _reply(self, code: int, body: str, ctype: str):
            data = body.encode()
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):  # noqa: N802 - stdlib API
            if self.path.startswith("/metrics"):
                body = to_prometheus(state.collect()) + \
                    broker_prometheus(state.collect_brokers()) + \
                    metricsd_prometheus(state.collect_metricsd()) + \
                    cluster_prometheus(state.collect_cluster())
                self._reply(200, body, "text/plain; version=0.0.4")
            elif self.path.startswith("/json"):
                self._reply(200, json.dumps(
                    {"regions": state.collect(),
                     "brokers": state.collect_brokers(),
                     "metricsd": state.collect_metricsd(),
                     "cluster": state.collect_cluster()}, indent=2),
                    "application/json")
            elif self.path.startswith("/healthz"):
                self._reply(200, "ok\n", "text/plain")
            else:
                self._reply(404, "not found\n", "text/plain")

    return Handler


def make_server(port: int, scan: Optional[str] = None,
                regions: Optional[List[str]] = None,
                host: str = "127.0.0.1",
                brokers: Optional[List[str]] = None,
                metricsd: Optional[List[str]] = None,
                cluster: Optional[str] = None
                ) -> ThreadingHTTPServer:
    state = MetricsState(scan, regions or [], brokers or [],
                         metricsd or [], cluster)
    srv = ThreadingHTTPServer((host, port), make_handler(state))
    srv.state = state  # type: ignore[attr-defined]
    return srv


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="vtpu-metrics")
    ap.add_argument("--port", type=int,
                    default=int(os.environ.get("VTPU_METRICS_PORT", "8431")))
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--scan", default=None,
                    help="directory of per-pod shared regions (node mode)")
    ap.add_argument("--region", action="append", default=[])
    ap.add_argument("--broker", action="append", default=[],
                    help="broker MAIN socket (repeatable): adds "
                         "per-tenant gauges (spill, residency, "
                         "suspension) via the host-side admin socket")
    ap.add_argument("--metricsd", action="append", default=[],
                    metavar="HOST:PORT",
                    help="vtpu-metricsd MetricService address "
                         "(repeatable): adds vtpu_metricsd_* gauges — "
                         "liveness, pass-through counters and the "
                         "virtualized values tenants observe")
    ap.add_argument("--cluster", default=os.environ.get(
        "VTPU_CLUSTER_SOCKET") or None, metavar="SOCKET",
        help="federation coordinator socket: adds vtpu_cluster_* "
             "gauges (membership, placements, migrations, ledger "
             "size/conservation — docs/FEDERATION.md)")
    ns = ap.parse_args(argv)
    srv = make_server(ns.port, ns.scan, ns.region, ns.host, ns.broker,
                      ns.metricsd, ns.cluster)
    log.info("vtpu-metrics serving on %s:%d (/metrics /json /healthz)",
             ns.host, ns.port)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
