"""vtpu tpu-info — quota-adjusted chip table, tpu-info style.

The real ``tpu-info`` CLI reads libtpu's localhost metrics service and
prints per-chip HBM usage and duty cycle — against the RAW chip, so a
time-share tenant would see the full 16 GB and its co-tenants' load.
This replacement presents the CONTAINER's view: HBM totals are the vTPU
quota, usage is the tenant's accounted usage, and duty cycle is sampled
from the shared region's cumulative busy time (reference §2.9f — the
nvidia-smi virtualization analogue, ``nvmlDeviceGetMemoryInfo`` /
``nvmlDeviceGetUtilizationRates`` hooks).

  python -m vtpu.tools.tpu_info            # in-container (env region)
  python -m vtpu.tools.tpu_info --region /path/to/vtpushr.cache
  python -m vtpu.tools.tpu_info --json

The duty cycle needs two samples; --interval sets the window.
"""

from __future__ import annotations

import argparse
import json
import os
import time
from typing import Dict, List, Optional

from ..shim.core import SharedRegion
from ..utils import envspec


def sample(region: SharedRegion, interval: float) -> List[Dict]:
    before = [region.device_stats(d) for d in range(region.ndevices)]
    # Keyed by (pid, host_pid): the namespaced pid alone collides across
    # containers (every pod's workload is its namespace's pid 1).
    pbefore = {(p.pid, p.host_pid): list(p.busy_us)
               for p in region.proc_stats()}
    t0 = time.monotonic()
    time.sleep(interval)
    elapsed_us = (time.monotonic() - t0) * 1e6
    out = []
    procs_after = region.proc_stats()
    for d in range(region.ndevices):
        st = region.device_stats(d)
        busy_delta = st.busy_us - before[d].busy_us
        duty = min(busy_delta / elapsed_us * 100.0, 100.0) \
            if elapsed_us > 0 else 0.0
        if st.limit_bytes == 0 and st.used_bytes == 0 and st.n_procs == 0 \
                and busy_delta == 0:
            continue
        # Per-process share of this device's window (the reference's
        # nvmlDeviceGetProcessUtilization merge): which TENANT is
        # consuming the granted share.
        procs = []
        for p in procs_after:
            prev = pbefore.get((p.pid, p.host_pid))
            # max(.., 0): a swept-and-recycled slot can report lower
            # counters than the before-snapshot.
            delta = max(p.busy_us[d] - (prev[d] if prev else 0), 0)
            if delta <= 0 and not p.used_bytes[d]:
                continue
            procs.append({
                "pid": int(p.pid), "host_pid": int(p.host_pid),
                "hbm_used_bytes": int(p.used_bytes[d]),
                "duty_cycle_pct": round(
                    min(delta / elapsed_us * 100.0, 100.0), 1)
                if elapsed_us > 0 else 0.0,
            })
        out.append({
            "device": d,
            "hbm_used_bytes": int(st.used_bytes),
            "hbm_limit_bytes": int(st.limit_bytes),
            "hbm_peak_bytes": int(st.peak_bytes),
            "duty_cycle_pct": round(duty, 1),
            "core_limit_pct": int(st.core_limit_pct),
            "n_procs": int(st.n_procs),
            "procs": procs,
        })
    return out


def _gib(n: int) -> str:
    return f"{n / 2**30:.2f} GiB"


def render(devs: List[Dict]) -> str:
    lines = [
        "TPU (vTPU quota view)",
        f"{'Chip':<6} {'HBM usage':<24} {'Duty cycle':<12} "
        f"{'Core cap':<10} {'Procs':<5}",
    ]
    for d in devs:
        lim = _gib(d["hbm_limit_bytes"]) if d["hbm_limit_bytes"] \
            else "unlimited"
        lines.append(
            f"{d['device']:<6} "
            f"{_gib(d['hbm_used_bytes']) + ' / ' + lim:<24} "
            f"{str(d['duty_cycle_pct']) + '%':<12} "
            f"{(str(d['core_limit_pct']) + '%') if d['core_limit_pct'] else '-':<10} "
            f"{d['n_procs']:<5}")
        for p in d.get("procs", []):
            lines.append(
                f"       pid {p['pid']:<8} (host {p['host_pid']:<8}) "
                f"{_gib(p['hbm_used_bytes']):<12} "
                f"{str(p['duty_cycle_pct']) + '%':<8}")
    if len(lines) == 2:
        lines.append("(no active vTPU devices)")
    return "\n".join(lines)


def find_region() -> Optional[str]:
    env_path = os.environ.get(envspec.ENV_SHARED_CACHE)
    if env_path and os.path.exists(env_path):
        return env_path
    return None


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="tpu-info (vtpu)")
    ap.add_argument("--region", default=None)
    ap.add_argument("--interval", type=float, default=1.0,
                    help="duty-cycle sampling window (s)")
    ap.add_argument("--json", action="store_true")
    ns = ap.parse_args(argv)

    path = ns.region or find_region()
    if not path:
        print("no vTPU accounting region "
              f"(set {envspec.ENV_SHARED_CACHE} or --region)")
        return 1
    region = SharedRegion(path)
    try:
        devs = sample(region, ns.interval)
    finally:
        region.close()
    if ns.json:
        print(json.dumps(devs, indent=2))
    else:
        print(render(devs))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
