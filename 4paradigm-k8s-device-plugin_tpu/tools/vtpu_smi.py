"""vtpu-smi — quota/usage monitor over vTPU shared accounting regions.

The reference virtualizes NVML so in-container ``nvidia-smi`` shows the
quota-adjusted view (reference §2.9f: nvmlDeviceGetMemoryInfo hook,
``get_gpu_memory_monitor``); node operators read every container's shrreg
via the VGPU_MONITOR_MODE shared dirs (reference server.go:494-501).
vtpu-smi is both of those: run it inside a container (it finds the
region from VTPU_DEVICE_MEMORY_SHARED_CACHE) or on the node against
``/usr/local/vtpu/shared`` to see every pod.

  vtpu-smi                      # in-container view
  vtpu-smi --scan /usr/local/vtpu/shared   # node monitor view
  vtpu-smi --json               # machine-readable
  vtpu-smi --sweep-host         # reclaim slots of dead host pids (node)

vtpu-trace surfaces (docs/TRACING.md):

  vtpu-smi trace --broker /run/vtpu.sock             # all tenants
  vtpu-smi trace tenant-a --broker /run/vtpu.sock    # one tenant
  vtpu-smi trace --broker ... --dump chrome.json     # Chrome/Perfetto
  vtpu-smi leases               # chip-lease sidecar forensics

``trace`` reads the broker's flight recorder over the BIND-FREE TRACE
verb on the MAIN socket (no tenant slot, no chip claim — the same
no-wedge rationale as the STATS probe); ``--dump`` also merges any
shim-side native ring events found next to the scanned regions.
``leases`` names the current chip-lease holder (pid, cmdline, stage,
heartbeat age) and flags stale/dead holders explicitly.

Run as: python -m vtpu.tools.vtpu_smi
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional

from ..shim.core import SharedRegion
from ..utils import envspec


def find_regions(scan: Optional[str]) -> List[str]:
    # *.chip<k> variants are the multi-chip broker's per-chip regions
    # (runtime/server.py chip_region_path).
    if scan:
        pats = [os.path.join(scan, "*", "vtpushr.cache"),
                os.path.join(scan, "*", "vtpushr.cache.chip*"),
                os.path.join(scan, "*.cache"),
                os.path.join(scan, "*.cache.chip*"),
                os.path.join(scan, "*.shr"),
                os.path.join(scan, "*.shr.chip*")]
        out: List[str] = []
        for pat in pats:
            out.extend(sorted(glob.glob(pat)))
        return out
    env_path = os.environ.get(envspec.ENV_SHARED_CACHE)
    if env_path and os.path.exists(env_path):
        return [env_path] + sorted(glob.glob(env_path + ".chip*"))
    return sorted(glob.glob("/tmp/vtpu*.cache")
                  + glob.glob("/tmp/vtpu*.cache.chip*"))


def read_region(path: str, sweep_host: bool = False) -> Dict:
    r = SharedRegion(path)
    try:
        if sweep_host:
            r.sweep_dead_host()
        devices = []
        for d in range(r.ndevices):
            st = r.device_stats(d)
            devices.append({
                "device": d,
                "limit_bytes": int(st.limit_bytes),
                "used_bytes": int(st.used_bytes),
                "peak_bytes": int(st.peak_bytes),
                "core_limit_pct": int(st.core_limit_pct),
                "n_procs": int(st.n_procs),
            })
        procs = []
        for st in r.proc_stats():
            procs.append({
                "pid": int(st.pid),
                "host_pid": int(st.host_pid),
                "used_bytes": [int(b) for b in
                               st.used_bytes[:r.ndevices]],
                # per-device cumulative device time: which TENANT is
                # consuming the chip (reference per-process utilization,
                # nvmlDeviceGetProcessUtilization)
                "busy_us": [int(b) for b in st.busy_us[:r.ndevices]],
            })
        return {"region": path, "devices": devices, "procs": procs}
    finally:
        r.close()


def _mb(n: int) -> str:
    return f"{n / 2**20:,.0f}MiB"


def render(infos: List[Dict]) -> str:
    lines = []
    lines.append("+" + "-" * 74 + "+")
    lines.append("| vtpu-smi — virtual TPU quota monitor" + " " * 37 + "|")
    lines.append("+" + "-" * 74 + "+")
    for info in infos:
        lines.append(f"| region: {info['region'][:64]:<64} |")
        lines.append("| dev |       used /      limit (      peak) "
                     "| core% | procs |" + " " * 12 + "|")
        for d in info["devices"]:
            if d["limit_bytes"] == 0 and d["used_bytes"] == 0 \
                    and d["n_procs"] == 0:
                continue
            lim = _mb(d["limit_bytes"]) if d["limit_bytes"] else "unlimited"
            core = f"{d['core_limit_pct']}%" if d["core_limit_pct"] else "-"
            row = (f"| {d['device']:>3} | {_mb(d['used_bytes']):>10} / "
                   f"{lim:>10} ({_mb(d['peak_bytes']):>10}) "
                   f"| {core:>5} | {d['n_procs']:>5} |")
            lines.append(row + " " * max(0, 76 - len(row)) + "|")
        for p in info["procs"]:
            used = sum(p["used_bytes"])
            busy = sum(p.get("busy_us", []))
            row = (f"|   pid {p['pid']:>7} (host {p['host_pid']:>7}) "
                   f"uses {_mb(used):>10}  busy {busy / 1e6:>8.1f}s")
            lines.append(row + " " * max(0, 75 - len(row)) + "|")
        lines.append("+" + "-" * 74 + "+")
    if not infos:
        lines.append("no vTPU accounting regions found")
    return "\n".join(lines)


def _admin_request(broker_socket: str, msg: dict,
                   timeout: float = 10.0) -> dict:
    """One request over the broker's host-side admin socket
    (<socket>.admin — suspend/resume/stats; see runtime/protocol.py)."""
    import socket as socketmod

    from ..runtime import protocol as P
    s = socketmod.socket(socketmod.AF_UNIX, socketmod.SOCK_STREAM)
    s.settimeout(timeout)
    try:
        s.connect(broker_socket + ".admin")
        P.send_msg(s, msg)
        return P.recv_msg(s)
    finally:
        s.close()


def _main_request(broker_socket: str, msg: dict,
                  timeout: float = 10.0) -> dict:
    """One BIND-FREE request over the broker's MAIN socket (STATS /
    TRACE verbs answer without a HELLO, so this can never claim a
    tenant slot or wedge a chip claim)."""
    import socket as socketmod

    from ..runtime import protocol as P
    s = socketmod.socket(socketmod.AF_UNIX, socketmod.SOCK_STREAM)
    s.settimeout(timeout)
    try:
        s.connect(broker_socket)
        P.send_msg(s, msg)
        return P.recv_msg(s)
    finally:
        s.close()


def collect_ring_events(paths: List[str]) -> List[dict]:
    """Shim-side native ring events (rate waits, mem stalls) from the
    ``<region>.trace.<pid>`` files next to the given regions."""
    from ..shim.core import TraceRing
    out: List[dict] = []
    for rp in paths:
        for ring_path in sorted(glob.glob(rp + ".trace.*")):
            try:
                pid = int(ring_path.rsplit(".", 1)[-1])
            except ValueError:
                pid = 0
            try:
                with TraceRing(ring_path) as ring:
                    evs, _ = ring.read(0, 4096)
            except OSError as e:
                print(f"skipping ring {ring_path}: {e}", file=sys.stderr)
                continue
            for ev in evs:
                ev["pid"] = pid
                ev["ring"] = ring_path
            out.extend(evs)
    out.sort(key=lambda e: e.get("t_ns", 0))
    return out


def cmd_trace(ns, paths: List[str]) -> int:
    """`vtpu-smi trace [TENANT]`: flight-recorder spans + slow-op
    captures, human or --json, --dump FILE for Chrome/Perfetto."""
    from ..runtime import protocol as P
    from ..runtime import trace as tracing
    if not ns.broker:
        print("trace needs --broker <main socket>", file=sys.stderr)
        return 2
    msg: dict = {"kind": P.TRACE}
    if ns.cmd_arg:
        msg["tenant"] = ns.cmd_arg
    if ns.limit:
        msg["limit"] = ns.limit
    resp = _main_request(ns.broker, msg)
    if not resp.get("ok"):
        print(json.dumps(resp, indent=2))
        return 1
    tenants = resp.get("tenants", {})
    if ns.dump:
        ring_events = collect_ring_events(paths)
        doc = tracing.chrome_trace(tenants, ring_events)
        with open(ns.dump, "w") as f:
            json.dump(doc, f)
        print(f"wrote {len(doc['traceEvents'])} trace events to "
              f"{ns.dump} (load in chrome://tracing or Perfetto)")
        return 0
    if ns.json:
        print(json.dumps(resp, indent=2))
        return 0
    if not resp.get("enabled"):
        print("tracing is disabled on this broker (set VTPU_TRACE=1)")
    for name, body in sorted(tenants.items()):
        spans = body.get("spans", [])
        caps = body.get("captures", [])
        print(f"tenant {name}: {len(spans)} spans, "
              f"{len(caps)} slow-op captures")
        for s in spans[-(ns.limit or 10):]:
            print(f"  {s.get('trace', '-'):>16} {s.get('key', '?'):<12}"
                  f" queue {s.get('queue_us', 0):>9.0f}us"
                  f" bucket {s.get('bucket_us', 0):>9.0f}us"
                  f" device {s.get('device_us', 0):>9.0f}us"
                  f" total {s.get('total_us', 0):>9.0f}us"
                  + (" ERROR" if s.get("error") else ""))
        for cap in caps[-3:]:
            ctx = cap.get("context", {})
            print(f"  SLOW {cap.get('factor')}x est "
                  f"{cap.get('est_us')}us: qdepth="
                  f"{ctx.get('queue_depth')} bucket="
                  f"{ctx.get('bucket_level_us')}us hbm_free="
                  f"{ctx.get('hbm_headroom_bytes')} co="
                  f"{','.join(ctx.get('co_tenants', [])) or '-'}")
    return 0


def cmd_metricsd(ns) -> int:
    """`vtpu-smi metricsd [ADDR]`: query a vtpu-metricsd instance over
    its own MetricService wire and print the quota-virtualized view a
    stock in-container tpu-info would see (docs/METRICSD.md)."""
    import grpc

    from ..metricsd import DEFAULT_PORT
    from ..metricsd import server as metricsd_server
    from ..proto import tpu_metrics_grpc as mrpc
    from ..proto import tpu_metrics_pb2 as mpb
    addr = ns.cmd_arg or os.environ.get("VTPU_METRICSD_BROKER") \
        or f"localhost:{os.environ.get('VTPU_METRICSD_PORT', DEFAULT_PORT)}"
    ch = grpc.insecure_channel(addr)
    stub = mrpc.RuntimeMetricServiceStub(ch)
    out: Dict = {"metricsd": addr, "metrics": {}}
    try:
        listed = stub.ListSupportedMetrics(
            mpb.ListSupportedMetricsRequest(), timeout=3.0)
        out["supported"] = [sm.metric_name
                            for sm in listed.supported_metric]
        for name in metricsd_server.VIRTUALIZED_METRICS + \
                metricsd_server.SELF_METRICS:
            resp = stub.GetRuntimeMetric(
                mpb.MetricRequest(metric_name=name), timeout=3.0)
            vals = {}
            for m in resp.metric.metrics:
                dev = int(m.attribute.value.int_attr) \
                    if m.attribute.key else -1
                vals[dev] = (m.gauge.as_double
                             if m.gauge.WhichOneof("value") == "as_double"
                             else int(m.gauge.as_int))
            out["metrics"][name] = vals
    except grpc.RpcError as e:
        print(f"metricsd {addr} unreachable: {e.code().name}",
              file=sys.stderr)
        return 1
    finally:
        ch.close()
    if ns.json:
        print(json.dumps(out, indent=2))
        return 0
    print(f"vtpu-metricsd @ {addr} (the stock tpu-info view)")
    totals = out["metrics"].get(metricsd_server.METRIC_HBM_TOTAL, {})
    usages = out["metrics"].get(metricsd_server.METRIC_HBM_USAGE, {})
    duties = out["metrics"].get(metricsd_server.METRIC_DUTY_CYCLE, {})
    print(f"{'Dev':<5} {'HBM usage':<26} {'Duty (of quota)':<16}")
    for dev in sorted(totals):
        used, total = usages.get(dev, 0), totals[dev]
        print(f"{dev:<5} {_mb(used) + ' / ' + _mb(total):<26} "
              f"{str(duties.get(dev, 0.0)) + '%':<16}")
    reqs = out["metrics"].get(metricsd_server.METRIC_SELF_REQUESTS, {})
    denied = out["metrics"].get(metricsd_server.METRIC_SELF_DENIED, {})
    print(f"requests served: {sum(reqs.values())}, "
          f"pass-through denials: {sum(denied.values())}")
    return 0


def _top_rows(slo_resp: dict, stats_resp: dict) -> List[Dict]:
    """Join one SLO reply with one STATS reply into renderable rows."""
    rows = []
    stats = (stats_resp or {}).get("tenants") or {}
    for name, body in sorted((slo_resp.get("tenants") or {}).items()):
        ph = body.get("phases", {})
        wins = body.get("windows", {})
        short = wins[min(wins, key=float)] if wins else {}
        fair = ((slo_resp.get("fairness") or {}).get("tenants")
                or {}).get(name, {})
        st = stats.get(name, {})
        rows.append({
            "tenant": name,
            "steps_per_s": short.get("steps_per_s", 0.0),
            "p50_queue_us": ph.get("queue", {}).get("p50_us", 0.0),
            "p99_queue_us": ph.get("queue", {}).get("p99_us", 0.0),
            "p50_e2e_us": ph.get("e2e", {}).get("p50_us", 0.0),
            "p99_e2e_us": ph.get("e2e", {}).get("p99_us", 0.0),
            "p99_device_us": ph.get("device", {}).get("p99_us", 0.0),
            "attainment_pct": short.get("attainment_pct", 100.0),
            "burn_rate": short.get("burn_rate", 0.0),
            "burn_alert": body.get("burn_alert", False),
            "fair_ratio": fair.get("ratio"),
            "top_blamer": body.get("top_blamer"),
            "hbm_used": st.get("used_bytes", 0),
            "suspended": st.get("suspended", False),
            # vtpu-elastic (docs/SCHEDULING.md): burst-credit balance,
            # preemption park state and shed counters ride the same
            # bind-free STATS reply.
            "credit_ms": round(st.get("credit_us", 0) / 1e3, 1),
            "preempted": st.get("preempted", False),
            "preemptions": st.get("preemptions", 0),
            "shed": st.get("shed_total", 0),
            # vtpu-fastlane (docs/PERF.md): which data plane the
            # tenant is on — ring-admitted vs brokered-fallback steps
            # and the live ring depth.
            "fastlane": st.get("fastlane"),
        })
    rows.sort(key=lambda r: -r["steps_per_s"])
    return rows


def render_top(rows: List[Dict], enabled: bool = True,
               jain: Optional[float] = None) -> str:
    """The htop-style per-tenant SLO table (docs/OBSERVABILITY.md)."""
    hdr = (f"{'TENANT':<18} {'STEPS/S':>8} {'P50 E2E':>9} "
           f"{'P99 E2E':>9} {'P99 QUE':>9} {'P99 DEV':>9} "
           f"{'ATTAIN%':>8} {'BURN':>6} {'FAIR':>5} {'CREDIT':>8} "
           f"{'SHED':>5} {'PLANE':>6} {'TOP BLAMER':<16}")
    lines = ["vtpu-smi top — per-tenant SLO / fairness / blame"
             + (f"  (jain={jain})" if jain is not None else "")
             + ("" if enabled else "  [SLO PLANE DISABLED: VTPU_SLO=0]"),
             hdr, "-" * len(hdr)]
    for r in rows:
        # State flag: '!' burn alert, 's' admin-suspended, 'p'
        # preemption-parked (docs/SCHEDULING.md).
        flag = "!" if r["burn_alert"] else (
            "s" if r["suspended"] else (
                "p" if r.get("preempted") else " "))
        fair = (f"{r['fair_ratio']:.2f}" if r["fair_ratio"] is not None
                else "-")
        credit = f"{r.get('credit_ms', 0):.0f}ms"
        # Data plane: 'ring' when a fastlane lane exists and EVERY
        # chip ring's gate is open (the stats rollup reports the
        # worst gate and the max depth over a sharded lane's chips —
        # a lane hot on chip 1 but idle on chip 0 is still 'ring',
        # never 'sock'); 'held' while any ordinal is parked; 'sock'
        # with no lane or a closed one.  Sharded lanes show their
        # chip count ('ring2').
        fl = r.get("fastlane")
        plane = "sock"
        if fl:
            g = fl.get("gate", 2)
            if g == 0:
                nch = len(fl.get("chips") or ())
                plane = f"ring{nch}" if nch > 1 else "ring"
            elif g == 1:
                plane = "held"
        lines.append(
            f"{r['tenant'][:17]:<17}{flag} {r['steps_per_s']:>8.1f} "
            f"{r['p50_e2e_us']:>9.0f} {r['p99_e2e_us']:>9.0f} "
            f"{r['p99_queue_us']:>9.0f} {r['p99_device_us']:>9.0f} "
            f"{r['attainment_pct']:>8.2f} {r['burn_rate']:>6.1f} "
            f"{fair:>5} {credit:>8} {r.get('shed', 0):>5} "
            f"{plane:>6} {(r['top_blamer'] or '-')[:16]:<16}")
    if not rows:
        lines.append("(no tenants with SLO history)")
    return "\n".join(lines)


def cmd_top(ns) -> int:
    """``vtpu-smi top``: live htop-style per-tenant table — steps/s,
    p50/p99 by phase, SLO attainment, burn rate, top noisy-neighbor
    blamer — from the broker's always-on SLO plane over the host-side
    admin socket.  ``--once`` prints a single snapshot; ``--fake``
    renders a synthetic plane (CI wiring check, no broker needed)."""
    import time as timemod

    from ..runtime import protocol as P
    from ..runtime import slo as slo_lib
    if ns.fake:
        rep = slo_lib.fairness_smoke(n_tenants=8, seed=3)
        plane_rep = None
        # Re-run the smoke's plane for a renderable report.
        smoke_plane = slo_lib.SloPlane(enabled=True, windows=(30.0,),
                                       budget=0.01)
        for i in range(8):
            name = f"fake-{i}"
            smoke_plane.ensure_tenant(name, quota_pct=50)
            for k in range(64):
                smoke_plane.record(name, queue_us=100.0 * (i + 1),
                                   bucket_us=10.0, device_us=500.0,
                                   total_us=110.0 * (i + 1) + 500.0,
                                   wait_weights={f"fake-{(i+1) % 8}":
                                                 1.0})
        plane_rep = smoke_plane.report(
            admin=True, quota_pcts={f"fake-{i}": 50 for i in range(8)})
        rows = _top_rows(plane_rep, {})
        if ns.json:
            print(json.dumps({"smoke": rep, "rows": rows}, indent=2))
        else:
            print(render_top(rows,
                             jain=plane_rep["fairness"]["jain"]))
        return 0 if rep["ok"] else 1
    if not ns.broker:
        print("top needs --broker <main socket> (or --fake)",
              file=sys.stderr)
        return 2
    while True:
        slo_resp = _admin_request(ns.broker, {"kind": P.SLO})
        if not slo_resp.get("ok"):
            print(json.dumps(slo_resp, indent=2))
            return 1
        stats_resp = _admin_request(ns.broker, {"kind": P.STATS})
        rows = _top_rows(slo_resp, stats_resp)
        if ns.json:
            print(json.dumps({"rows": rows,
                              "fairness": slo_resp.get("fairness")},
                             indent=2))
        else:
            if not ns.once:
                print("\033[2J\033[H", end="")
            print(render_top(
                rows, enabled=slo_resp.get("enabled", False),
                jain=(slo_resp.get("fairness") or {}).get("jain")))
        if ns.once:
            return 0
        timemod.sleep(max(ns.interval, 0.2))


def cmd_leases(ns) -> int:
    """`vtpu-smi leases`: chip-lease sidecar forensics — who holds (or
    last held) each chip lease, liveness, heartbeat age."""
    from ..runtime import trace as tracing
    lease_paths = ns.lease_file or [tracing.lease_sidecar_path()]
    out = []
    for p in lease_paths:
        diag = tracing.diagnose_lease(p)
        diag["sidecar"] = p
        out.append(diag)
    if ns.json:
        print(json.dumps(out, indent=2))
    else:
        for diag in out:
            print(f"{diag['sidecar']}: "
                  f"{tracing.format_lease_diagnosis(diag)}")
    # Non-zero when a stale lease is blocking the chip: scripts (and the
    # bench gate) can branch on it.
    return 1 if any(d.get("stale") for d in out) else 0


def cmd_cluster(ns) -> int:
    """``vtpu-smi cluster <coordinator socket>`` — the federation
    operator view (docs/FEDERATION.md): node membership table (alive /
    heartbeat lag / chip inventory), placements, counters, and the
    coordinator's own ledger-conservation check — non-empty
    ``violations`` is a red alert, it means the authoritative ledger
    itself is inconsistent."""
    sock = ns.cmd_arg or os.environ.get(
        "VTPU_CLUSTER_SOCKET", "/usr/local/vtpu/vtpu-cluster.sock")
    from ..runtime import cluster
    try:
        st = cluster.status(sock)
    except OSError as e:
        print(f"coordinator unreachable at {sock}: {e}",
              file=sys.stderr)
        return 1
    if ns.json:
        print(json.dumps(st, indent=2))
        return 0 if st.get("ok") and not st.get("violations") else 1
    print(f"cluster epoch={st.get('epoch')} "
          f"generation={st.get('generation')} "
          f"policy={st.get('policy')} "
          f"placements={st.get('placements_total')} "
          f"migrations={st.get('migrations_total')} "
          f"ledger={st.get('ledger_bytes')}B")
    rows = [("NODE", "ALIVE", "CHIPS", "FREE", "TENANTS", "LAG")]
    for n in st.get("nodes") or []:
        lag = n.get("lag_s")
        rows.append((
            str(n.get("node")),
            "yes" if n.get("alive") else "DOWN",
            str(n.get("chips")),
            str(n.get("free")),
            ",".join(n.get("tenants") or []) or "-",
            f"{lag:.1f}s" if lag is not None else "-"))
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    for r in rows:
        print("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    for t, pl in sorted((st.get("placements") or {}).items()):
        print(f"  {t}: node={pl.get('node')} "
              f"chips={pl.get('chips')} hbm={pl.get('hbm')}")
    for v in st.get("violations") or []:
        print(f"VIOLATION: {v}", file=sys.stderr)
    return 0 if st.get("ok") and not st.get("violations") else 1


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="vtpu-smi")
    ap.add_argument("cmd", nargs="?", default=None,
                    choices=("trace", "leases", "analyze", "mc", "wmm",
                             "dmc", "metricsd", "chaos", "top",
                             "cluster"),
                    help="trace: flight-recorder spans (needs "
                         "--broker; --dump FILE exports Chrome-trace "
                         "JSON); leases: chip-lease sidecar forensics; "
                         "analyze: cross-layer invariant linters incl. "
                         "the shared-memory atomics checker "
                         "(docs/ANALYSIS.md); mc: deterministic model "
                         "checking of quota/lease/crash-recovery "
                         "invariants (--smoke for the quick wiring "
                         "check); wmm: weak-memory-model litmus "
                         "exploration of the shared-region lock-free "
                         "protocols (--smoke for the wiring check); "
                         "dmc: distributed model checking of the "
                         "cluster federation protocol under network "
                         "faults (--smoke for the wiring check); "
                         "metricsd: the quota-virtualized "
                         "view stock tpu-info sees (docs/METRICSD.md); "
                         "top: live htop-style per-tenant SLO / "
                         "fairness / blame table (needs --broker; "
                         "--once for one snapshot, --fake for the CI "
                         "wiring check — docs/OBSERVABILITY.md); "
                         "cluster: federation coordinator status — "
                         "node table, placements, ledger conservation "
                         "(cmd_arg = coordinator socket, "
                         "docs/FEDERATION.md)")
    ap.add_argument("cmd_arg", nargs="?", default=None,
                    help="tenant name for `trace`; HOST:PORT for "
                         "`metricsd`")
    ap.add_argument("--dump", default=None, metavar="FILE",
                    help="with `trace`: write Chrome-trace/Perfetto "
                         "JSON (broker spans + shim ring events)")
    ap.add_argument("--limit", type=int, default=0,
                    help="with `trace`: newest N spans per tenant")
    ap.add_argument("--lease-file", action="append", default=[],
                    metavar="PATH",
                    help="with `leases`: explicit sidecar path(s); "
                         "default VTPU_LEASE_SIDECAR")
    ap.add_argument("--scan", default=None,
                    help="directory of per-pod shared regions (node mode)")
    ap.add_argument("--region", action="append", default=[],
                    help="explicit region file (repeatable)")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--once", action="store_true",
                    help="with `top`: print one snapshot and exit")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="with `top`: refresh period, seconds")
    ap.add_argument("--fake", action="store_true",
                    help="with `top`: render a synthetic SLO plane "
                         "(no broker; the analyze CI job's wiring "
                         "check)")
    ap.add_argument("--smoke", action="store_true",
                    help="with `mc`/`wmm`/`dmc`/`chaos`: tiny-budget "
                         "wiring check (the analyze CI job's smokes)")
    ap.add_argument("--sweep-host", action="store_true",
                    help="reclaim slots of dead host pids (node mode only)")
    ap.add_argument("--broker", default=None, metavar="SOCKET",
                    help="broker MAIN socket; enables the admin verbs "
                         "below (talks to SOCKET.admin, host-only)")
    ap.add_argument("--suspend", default=None, metavar="TENANT",
                    help="hold TENANT's queue (reference "
                         "suspend_all analogue)")
    ap.add_argument("--resume", default=None, metavar="TENANT")
    ap.add_argument("--resize", default=None, metavar="TENANT",
                    help="live-resize TENANT's quotas without a "
                         "restart (RESIZE verb, journaled; combine "
                         "with --hbm/--core — docs/CHAOS.md)")
    ap.add_argument("--hbm", default=None, metavar="QTY",
                    help="with --resize: new per-chip HBM quota "
                         "(K8s quantity, replicated across the grant)")
    ap.add_argument("--core", type=int, default=None, metavar="PCT",
                    help="with --resize: new device-time share "
                         "(0-100; 0 = unmetered)")
    ap.add_argument("--migrate", default=None, metavar="TENANT",
                    help="live-migrate TENANT onto another chip "
                         "(MIGRATE verb, journaled; combine with "
                         "--device — docs/FAILOVER.md)")
    ap.add_argument("--device", type=int, default=None, metavar="CHIP",
                    help="with --migrate: the target chip index")
    ap.add_argument("--migrate-to", default=None, metavar="SOCKET",
                    help="cross-node migration (with --migrate and "
                         "--broker = SOURCE socket): target broker's "
                         "MAIN socket — drives the MIGRATE_OUT begin /"
                         " MIGRATE_IN / MIGRATE_OUT commit dance, "
                         "aborting on any failure "
                         "(docs/FEDERATION.md)")
    ap.add_argument("--chips", default=None, metavar="LIST",
                    help="comma-separated target chip indices for "
                         "--migrate-to (default: the source chip "
                         "layout, same-topology)")
    ap.add_argument("--repl-status", action="store_true",
                    help="replication block: role, follower lag, "
                         "fence generation, takeover count "
                         "(REPL_SYNC status probe — docs/FAILOVER.md)")
    ap.add_argument("--broker-stats", action="store_true",
                    help="per-tenant broker stats (quota, spill, "
                         "residency, suspension, journal/recovery)")
    ap.add_argument("--drain", action="store_true",
                    help="refuse new tenants, quiesce dispatch and "
                         "commit a final journal snapshot (handover "
                         "prep; docs/BROKER_RECOVERY.md)")
    ap.add_argument("--handover", action="store_true",
                    help="--drain, then exit the broker gracefully so "
                         "the supervisor's successor recovers the "
                         "journal (zero-downtime upgrade)")
    ap.add_argument("--shutdown", action="store_true",
                    help="stop the broker gracefully WITHOUT the drain "
                         "quiesce/snapshot (SHUTDOWN verb; prefer "
                         "--handover for zero-downtime upgrades)")
    ns = ap.parse_args(argv)

    if ns.cmd == "top":
        return cmd_top(ns)
    if ns.cmd == "cluster":
        return cmd_cluster(ns)
    if ns.cmd == "leases":
        return cmd_leases(ns)
    if ns.cmd == "metricsd":
        return cmd_metricsd(ns)
    if ns.cmd == "trace":
        return cmd_trace(ns, ns.region or find_regions(ns.scan))
    if ns.cmd == "analyze":
        # Static-analysis suite (tools/analyze): lock discipline, verb
        # exhaustiveness, env-flag contract, journal replay coverage.
        from .analyze import main as analyze_main
        return analyze_main(["--json"] if ns.json else [])
    if ns.cmd == "chaos":
        # vtpu-chaos (docs/CHAOS.md): deterministic fault schedules +
        # the kill -9 churn suite.  --smoke is the cheap wiring check
        # the analyze CI job runs (no jax, no processes); full
        # schedules live on `python -m vtpu.tools.chaos`.
        from .chaos import main as chaos_main
        args = []
        if ns.json:
            args.append("--json")
        if ns.smoke:
            args.append("--smoke")
        return chaos_main(args)
    if ns.cmd == "mc":
        # Model checker (tools/mc): interleaving + crash-cut engines
        # over the invariant registry (docs/ANALYSIS.md).  --smoke is
        # the cheap wiring check the analyze CI job runs; budgets and
        # selfcheck live on `python -m vtpu.tools.mc` directly.
        from .mc import main as mc_main
        args = []
        if ns.json:
            args.append("--json")
        if ns.smoke:
            args.append("--smoke")
        if ns.cmd_arg:
            args.extend(["--scenario", ns.cmd_arg])
        return mc_main(args)
    if ns.cmd == "dmc":
        # Distributed model checker (tools/dmc): the REAL federation
        # coordinator under exhaustive network nondeterminism, held
        # to the dmc rows of the mc invariant registry
        # (docs/ANALYSIS.md "Distributed model checking").  --smoke
        # is the cheap wiring check the analyze CI job runs; budgets,
        # the floor gate and selfcheck live on
        # `python -m vtpu.tools.dmc` directly.
        from .dmc import main as dmc_main
        args = []
        if ns.json:
            args.append("--json")
        if ns.smoke:
            args.append("--smoke")
        if ns.cmd_arg:
            args.extend(["--scenario", ns.cmd_arg])
        return dmc_main(args)
    if ns.cmd == "wmm":
        # Weak-memory litmus explorer (tools/wmm): the shared-region
        # lock-free protocols under C11-ish reordering, held to the
        # wmm rows of the mc invariant registry (docs/ANALYSIS.md
        # "Weak memory model").  --smoke is the cheap wiring check
        # the analyze CI job runs; budgets, the floor gate and
        # selfcheck live on `python -m vtpu.tools.wmm` directly.
        from .wmm import main as wmm_main
        args = []
        if ns.json:
            args.append("--json")
        if ns.smoke:
            args.append("--smoke")
        if ns.cmd_arg:
            args.extend(["--litmus", ns.cmd_arg])
        return wmm_main(args)

    admin_verbs = (ns.suspend or ns.resume or ns.resize or ns.migrate
                   or ns.repl_status or ns.broker_stats or ns.drain
                   or ns.handover or ns.shutdown)
    if admin_verbs and not ns.broker:
        ap.error("--suspend/--resume/--resize/--migrate/--repl-status/"
                 "--broker-stats/--drain/--handover/--shutdown need "
                 "--broker <main socket>")
    if ns.broker:
        from ..runtime import protocol as P
        if ns.suspend:
            resp = _admin_request(ns.broker, {"kind": P.SUSPEND,
                                              "tenant": ns.suspend})
        elif ns.resume:
            resp = _admin_request(ns.broker, {"kind": P.RESUME,
                                              "tenant": ns.resume})
        elif ns.resize:
            msg = {"kind": P.RESIZE, "tenant": ns.resize}
            if ns.hbm is not None:
                msg["hbm_limit"] = envspec.parse_quantity(ns.hbm)
            if ns.core is not None:
                msg["core_limit"] = int(ns.core)
            resp = _admin_request(ns.broker, msg)
        elif ns.migrate and ns.migrate_to:
            # Cross-node MIGRATE (docs/FEDERATION.md): quiesce +
            # serialize at the source, transfer + park at the target,
            # THEN tear the source copy down — commit only after the
            # target acked, so the cluster never holds less than one
            # copy.  Any failure aborts: the tenant resumes serving
            # at the source untouched.
            out = _admin_request(
                ns.broker, {"kind": P.MIGRATE_OUT,
                            "tenant": ns.migrate,
                            "phase": "begin"}, timeout=90.0)
            if not out.get("ok"):
                print(json.dumps(out, indent=2))
                return 1
            in_msg = {"kind": P.MIGRATE_IN, "tenant": ns.migrate,
                      "state": out.get("state"),
                      "blobs": out.get("blobs")}
            if ns.chips:
                in_msg["devices"] = [int(c) for c
                                     in ns.chips.split(",") if c]
            accepted = _admin_request(ns.migrate_to, in_msg,
                                      timeout=90.0)
            if accepted.get("ok"):
                resp = _admin_request(
                    ns.broker, {"kind": P.MIGRATE_OUT,
                                "tenant": ns.migrate,
                                "phase": "commit"}, timeout=90.0)
                resp["target"] = accepted
            else:
                _admin_request(ns.broker,
                               {"kind": P.MIGRATE_OUT,
                                "tenant": ns.migrate,
                                "phase": "abort"}, timeout=90.0)
                resp = accepted
        elif ns.migrate:
            msg = {"kind": P.MIGRATE, "tenant": ns.migrate}
            if ns.device is not None:
                msg["device"] = int(ns.device)
            resp = _admin_request(ns.broker, msg, timeout=90.0)
        elif ns.repl_status:
            resp = _admin_request(ns.broker,
                                  {"kind": P.REPL_SYNC, "status": True})
        elif ns.broker_stats:
            resp = _admin_request(ns.broker, {"kind": P.STATS})
        elif ns.drain:
            resp = _admin_request(ns.broker, {"kind": P.DRAIN},
                                  timeout=90.0)
        elif ns.handover:
            resp = _admin_request(ns.broker, {"kind": P.HANDOVER},
                                  timeout=90.0)
        elif ns.shutdown:
            resp = _admin_request(ns.broker, {"kind": P.SHUTDOWN})
        else:
            ap.error("--broker needs --suspend/--resume/--resize/"
                     "--migrate/--repl-status/--broker-stats/--drain/"
                     "--handover/--shutdown")
        print(json.dumps(resp, indent=2))
        return 0 if resp.get("ok") else 1

    paths = ns.region or find_regions(ns.scan)
    infos = []
    for p in paths:
        try:
            infos.append(read_region(p, ns.sweep_host))
        except OSError as e:
            print(f"skipping {p}: {e}", file=sys.stderr)
    if ns.json:
        print(json.dumps(infos, indent=2))
    else:
        print(render(infos))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
