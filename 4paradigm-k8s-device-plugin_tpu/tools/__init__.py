"""Operator tooling: vtpu-smi (quota/usage monitor)."""
